"""Profiling & efficiency counters: step timing, FLOPs, MFU, trace capture.

The reference's only perf instrumentation is `/usr/bin/time -p` around
genrank runs (SURVEY.md §5.1).  TPU-natively we report step time,
images/sec, and MFU (model FLOPs utilization = achieved FLOP/s over the
chip's peak) — the metric the BASELINE.md target (≥35% MFU) is defined in —
plus a `jax.profiler` trace context for deeper dives in XProf.
"""
from __future__ import annotations

import contextlib
import random
import time
from typing import Optional

import jax

# peak dense bf16 FLOP/s per chip by device kind substring (public numbers).
# Order matters: 'lite' variants must match before the bare generation
# (libtpu reports e.g. 'TPU v5 lite' for v5e but 'TPU v5' for v5p,
# 'TPU v6 lite' for v6e).
PEAK_FLOPS = (
    ("v5 lite", 197e12),   # v5e
    ("v5e", 197e12),
    ("v6 lite", 918e12),   # v6e (Trillium)
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5", 459e12),        # bare 'TPU v5' = v5p
    ("v6", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops(default: float = 197e12) -> float:
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # pragma: no cover — graftlint: disable=EXC001 (no-device probe: any backend failure means fall back to the analytic default)
        return default
    for sub, peak in PEAK_FLOPS:
        if sub in kind:
            return peak
    return default


def transformer_train_flops(dim: int, depth: int, seq_len: int, heads: int,
                            dim_head: int, ff_mult: int, vocab: int,
                            batch: int,
                            logits_flops: Optional[float] = None) -> float:
    """Analytic FLOPs for one *training* step (fwd + bwd ≈ 3x fwd) of a
    GEGLU decoder stack + logits head, matmul terms only.  ``logits_flops``
    overrides the forward head term for models whose head is not a single
    ``seq_len x vocab`` matmul (e.g. DALLE's phase-sliced head)."""
    inner = heads * dim_head
    per_layer = (
        2 * seq_len * dim * (3 * inner)        # qkv projection
        + 2 * seq_len * seq_len * inner * 2    # scores + attn·v
        + 2 * seq_len * inner * dim            # output projection
        + 2 * seq_len * dim * (ff_mult * dim * 2)  # GEGLU in
        + 2 * seq_len * (ff_mult * dim) * dim      # ff out
    )
    logits = (2 * seq_len * dim * vocab if logits_flops is None
              else logits_flops)
    fwd = depth * per_layer + logits
    return 3.0 * fwd * batch


def dalle_train_flops(cfg, batch: int) -> float:
    """FLOPs per train step for a DALLEConfig.

    Attention is counted dense (the convention sparse models quote MFU in,
    and what the default dense-masked path actually executes), and the
    logits head is counted as the phase-sliced matmuls the dense and
    pipeline training losses really run (models/dalle.py::loss_from_hidden
    slices positions before the head dot): ``text_seq_len`` positions x
    text vocab (incl. per-position pads) + ``image_seq_len`` positions x
    image vocab — not a ``seq_len x total_vocab`` product, which would
    overstate FLOPs (and MFU) by ~9% at the CUB geometry.  The
    sequence-parallel loss (``_sp_loss``) still executes the full-vocab
    head per shard position (shards straddle the phase boundary at traced
    offsets), so sp runs report conservatively: achieved FLOP/s/MFU there
    understate executed work by the same ~9% rather than overstating it."""
    logits_fwd = 2 * cfg.dim * (
        cfg.text_seq_len * cfg.total_text_tokens
        + cfg.image_seq_len * cfg.num_image_tokens)
    return transformer_train_flops(
        dim=cfg.dim, depth=cfg.depth, seq_len=cfg.seq_len + 1,
        heads=cfg.heads, dim_head=cfg.dim_head, ff_mult=4,
        vocab=cfg.total_tokens, batch=batch, logits_flops=logits_fwd)


def dalle_prefill_flops(cfg) -> float:
    """Analytic forward FLOPs of ONE batch-1 prompt prefill (the
    ``text_seq_len + 1`` prompt positions through the stack, attention
    counted dense, plus the single-position logits head) — what a
    radix-prefix-cache hit SAVES (serve/prefix.py accounts hits in these
    units so /metrics and obs_report can state the avoided work in a
    hardware-meaningful number rather than a raw hit count)."""
    n = cfg.text_seq_len + 1
    inner = cfg.heads * cfg.dim_head
    per_layer = (
        2 * n * cfg.dim * (3 * inner)        # qkv projection
        + 2 * n * n * inner * 2              # scores + attn·v
        + 2 * n * inner * cfg.dim            # output projection
        + 2 * n * cfg.dim * (4 * cfg.dim * 2)    # GEGLU in
        + 2 * n * (4 * cfg.dim) * cfg.dim        # ff out
    )
    head = 2.0 * cfg.dim * cfg.total_tokens  # first-image-token logits
    return float(cfg.depth * per_layer + head)


def dalle_decode_cache_bytes(cfg, batch: int) -> int:
    """Bytes of KV-cache state one decode step carries (each of depth x
    (k, v) caches at [batch, heads, seq_len, dim_head]) — the decode
    loop's dominant HBM stream (PERF.md: the loop is measured
    bandwidth-bound on cache reads, sliced-KV 2.16x).  The storage dtype
    follows ``cfg.kv_cache_int8`` (one byte per element PLUS the f32
    per-head scale planes [batch, heads, 1, 1] each cache carries —
    counting the payload without the scales would let the cost-model
    gate under-measure the true stream), then ``cfg.kv_cache_bf16``
    (bf16 even at f32 activations; the knob's whole point), then the
    activation dtype when that is already half-width.
    ``tests/test_perf_model.py`` pins the compiled decode step's cache
    I/O against this number."""
    import jax.numpy as jnp

    n_caches = cfg.depth * 2  # k and v per layer
    if cfg.kv_cache_int8:
        itemsize = 1
    elif cfg.kv_cache_bf16 or jnp.dtype(cfg.dtype).itemsize == 2:
        itemsize = 2
    else:
        itemsize = 4
    total = (n_caches * batch * cfg.heads * cfg.seq_len * cfg.dim_head
             * itemsize)
    if cfg.kv_cache_int8:
        total += n_caches * batch * cfg.heads * 4  # f32 scale planes
    return total


def compiled_cost_summary(fn, *args, donate_argnums=(),
                          static_argnums=()) -> dict:
    """Compile ``fn(*args)`` (no execution, no device memory) and return
    XLA's own per-step cost model:

    ``flops``            HLO-level floating-point operation count
    ``bytes_accessed``   the cost model's total memory traffic.  NOTE:
                         XLA's accounting is per-op and pre-fusion-naive —
                         an operand read by k ops is counted k times — so
                         treat it as a *regression signal*, not achievable
                         HBM traffic; compare builds, don't quote it.
    ``temp_bytes``       peak temporary allocation of the compiled program
    ``argument_bytes`` / ``output_bytes``  I/O footprint

    This is the chip-independent half of the perf story: the same numbers
    XLA computes on any backend, so FLOPs/traffic/memory regressions are
    caught by CPU-only CI runs without a TPU in the loop (the wall-clock
    half lives in bench.py / tools/perf_ab.py).  The analytic
    ``dalle_train_flops`` is validated against this path (96.4% agreement
    at the CUB geometry, tests/test_perf_model.py)."""
    compiled = jax.jit(fn, donate_argnums=donate_argnums,
                       static_argnums=static_argnums).lower(*args).compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    out = {"flops": ca.get("flops", 0.0),
           "bytes_accessed": ca.get("bytes accessed", 0.0)}
    try:
        ma = compiled.memory_analysis()
        out.update(temp_bytes=ma.temp_size_in_bytes,
                   argument_bytes=ma.argument_size_in_bytes,
                   output_bytes=ma.output_size_in_bytes)
    except Exception:  # pragma: no cover — graftlint: disable=EXC001 (optional XLA API: absence just skips the optional memory fields)
        pass
    return out


class StepTimer:
    """Wall-clock step timer with EMA, images/sec, MFU and loader-stall
    reporting.

    Call ``tick(batch)`` once per completed (synced) step.  MFU uses the
    analytic `flops_per_sample` when provided.  ``stall_s`` is the host
    time the step loop spent waiting on the input pipeline for this step
    (``DevicePrefetcher.last_wait_s``): the reported EMA and
    ``loader_stall_frac`` (stall over step time) make an *input-bound* run
    readable as such in monitor/bench output instead of masquerading as a
    slow chip — at ~0 the step is device-bound, near 1 the chip is idling
    on the loader.

    Besides the EMAs (unchanged — the smooth "now" the logs show), raw
    per-step samples feed a bounded uniform reservoir (Vitter's Algorithm
    R, deterministic generator) so :meth:`percentiles` can report p50/p99
    step time and stall over the WHOLE run in O(reservoir) memory — the
    tail behavior EMAs structurally cannot show, consumed by
    ``tools/obs_report.py`` via the run's ``perf_summary`` event.
    """

    def __init__(self, flops_per_step: Optional[float] = None,
                 ema: float = 0.9, reservoir: int = 512):
        self.flops_per_step = flops_per_step
        self.ema = ema
        self.avg_dt: Optional[float] = None
        self.avg_stall: Optional[float] = None
        self._last: Optional[float] = None
        self._res_cap = int(reservoir)
        self._res_rng = random.Random(0x5eed)
        self._dt_res: list = []
        self._dt_n = 0
        self._stall_res: list = []
        self._stall_n = 0
        # flops_per_step covers the global batch, so peak spans all chips
        self.peak = device_peak_flops() * max(1, jax.device_count())

    def _reservoir_add(self, res: list, n: int, value: float) -> None:
        """Algorithm R: after n samples every one had cap/n odds of being
        in the reservoir — percentiles cover the run, not just its tail."""
        if len(res) < self._res_cap:
            res.append(value)
        else:
            j = self._res_rng.randrange(n)
            if j < self._res_cap:
                res[j] = value

    def tick(self, batch: int = 1, stall_s: Optional[float] = None) -> dict:
        now = time.perf_counter()
        out: dict = {}
        if self._last is not None:
            dt = now - self._last
            self.avg_dt = (dt if self.avg_dt is None
                           else self.ema * self.avg_dt + (1 - self.ema) * dt)
            self._dt_n += 1
            self._reservoir_add(self._dt_res, self._dt_n, dt)
            out["step_time_s"] = self.avg_dt
            out["images_per_sec"] = batch / self.avg_dt
            if self.flops_per_step:
                out["mfu"] = self.flops_per_step / self.avg_dt / self.peak
            if stall_s is not None:
                self.avg_stall = (stall_s if self.avg_stall is None
                                  else self.ema * self.avg_stall
                                  + (1 - self.ema) * stall_s)
                self._stall_n += 1
                self._reservoir_add(self._stall_res, self._stall_n, stall_s)
                out["loader_stall_s"] = self.avg_stall
                out["loader_stall_frac"] = min(
                    self.avg_stall / self.avg_dt, 1.0)
        self._last = now
        return out

    def percentiles(self) -> dict:
        """p50/p99 of raw step time and stall over the reservoir samples
        (``reservoir_n`` = steps observed).  Empty dict before step 2."""
        def pct(values, q):
            ordered = sorted(values)
            idx = min(int(round((q / 100.0) * (len(ordered) - 1))),
                      len(ordered) - 1)
            return ordered[idx]

        out: dict = {}
        if self._dt_res:
            out["reservoir_n"] = self._dt_n
            out["step_time_p50"] = pct(self._dt_res, 50)
            out["step_time_p99"] = pct(self._dt_res, 99)
        if self._stall_res:
            out["stall_p50"] = pct(self._stall_res, 50)
            out["stall_p99"] = pct(self._stall_res, 99)
        return out


@contextlib.contextmanager
def profile_trace(logdir: str = "/tmp/jax-trace", enabled: bool = True):
    """`jax.profiler` trace context (view with XProf/TensorBoard).

    Delegates to :func:`obs.prof.capture` — the repo's one managed
    profiler entry point (graftlint OBS003) — so the trace window also
    lands as a ``prof.xprof`` span in the telemetry stream."""
    if not enabled:
        yield
        return
    from ..obs import prof

    with prof.capture(logdir):
        yield
