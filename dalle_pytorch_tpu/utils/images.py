"""Host-side image saving helpers shared by the CLIs.

Replaces torchvision's ``save_image(..., normalize=True)`` / ``make_grid``
surface used across the reference scripts (train_vae.py:196-207,
generate.py:114-115, genrank.py:47-51): our decoders already emit [0, 1]
floats, so a clip + uint8 PNG/JPEG write is the equivalent.
"""
from __future__ import annotations

from pathlib import Path

import numpy as np


def to_uint8(img: np.ndarray) -> np.ndarray:
    return (np.clip(np.asarray(img, np.float32), 0.0, 1.0) * 255).astype(np.uint8)


def save_image(path: str | Path, img: np.ndarray) -> None:
    """Save one [h, w, 3] float image in [0, 1]."""
    from PIL import Image

    Path(path).parent.mkdir(parents=True, exist_ok=True)
    Image.fromarray(to_uint8(img)).save(path)


def save_image_grid(path: str | Path, images: np.ndarray, pad: int = 2) -> None:
    """Save a [n, h, w, 3] float batch as one horizontal strip."""
    from PIL import Image

    images = np.clip(np.asarray(images, dtype=np.float32), 0.0, 1.0)
    n, h, w, c = images.shape
    grid = np.ones((h, n * (w + pad) - pad, c), dtype=np.float32)
    for i, img in enumerate(images):
        grid[:, i * (w + pad): i * (w + pad) + w] = img
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    Image.fromarray((grid * 255).astype(np.uint8)).save(path)
