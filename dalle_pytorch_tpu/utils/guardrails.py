"""Training health guardrails: sentinels, anomaly policy, rollback, watchdog.

PR 3 made the trainers survive *loud* failures (kills, torn writes, bad
samples); this layer closes the *silent* ones a preemptible-pod run
actually dies from:

* a NaN/Inf gradient that poisons the optimizer state thousands of steps
  before anyone looks at a curve — caught **on device** by a per-step
  health vector (loss, global grad norm, finite flag, all computed inside
  the jitted step: no host sync in traced code) with the update suppressed
  by ``jnp.where`` masking (``optax.apply_if_finite``-style) so
  params/opt_state are never touched by a non-finite step;
* a loss spike or sustained divergence from pathological data — classified
  host-side by :class:`HealthMonitor` (rolling median + MAD robust
  z-score) and escalated: warn → (the device already skipped non-finite
  steps) → roll back to ``CheckpointManager.latest_valid()`` with the
  offending data window skipped and the LR backed off
  (:class:`RollbackAndSkip` caught by :func:`run_with_rollback`) → abort
  with ``ExitCode.ROLLBACK_BUDGET`` once the rollback budget is spent.
  Every escalation drops an atomic-rename **anomaly bundle**
  (``anomaly-{step:08d}/report.json``) for post-mortem;
* a wedged device call that hangs the step loop forever (the tunnel-wedge
  class DESIGN.md §6 fights in bench.py) — bounded by
  :class:`StepWatchdog`, a monotonic-clock thread armed around each step
  that dumps all-thread stacks and exits with ``ExitCode.WEDGED`` so the
  supervisors (``tools/monitor.py --restart-cmd``, the babysitter's
  ``BABYSIT_TRAIN_CMD`` loop) relaunch with ``--resume auto``.

Decision consistency: the health vector is an output of the one SPMD step
program, so under dp/fsdp/tp/pp every host reads identical values and the
skip/rollback decisions agree by construction (the same reasoning as
``GracefulShutdown.average_and_poll``).  Where a value is genuinely
per-shard — the sequence-parallel local loss inside ``shard_map`` —
:func:`collective_all_finite` combines the finite flags with
``lax.pmin`` over the mesh axes so all shards agree before any of them
decides to skip.

Chaos rehearsal (``GRAFT_FAULTS``, utils/faults.py): ``grad_nan:at_step=N``
and ``loss_spike:at_step=N`` drive :func:`fault_scale_for`, a traced
loss-scale input of the health-enabled train steps (``nan`` poisons the
real gradients on device; a large finite factor produces a genuine spike
whose update *does* land — exactly the state a rollback must discard);
``step_hang:at_step=N`` (``faults.maybe_hang``) wedges the step loop so
the watchdog's kill path is rehearsed end to end.  The suites:
tests/test_guardrails.py, tests/test_anomaly_resume.py.
"""
from __future__ import annotations

import collections
import json
import math
import os
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from . import faults
from ..obs import telemetry
from .failure import ExitCode

# observed loss multiplier for the loss_spike faultpoint: big enough that
# any sane spike_zscore fires, small enough that f32 grads stay finite
# (a non-finite "spike" would be caught by the sentinel instead, which is
# a different path than the one this fault exists to rehearse)
SPIKE_SCALE = 1e4

# --- device side: computed inside the jitted step (no host syncs) --------


def collective_all_finite(value, axis_names):
    """Inside a ``shard_map``/``pmap`` body: True iff every element of
    ``value`` is finite on EVERY shard of the given mesh axes.  The local
    flags are ``lax.pmin``-combined so all shards return the same answer —
    a skip decision must be collective or shards diverge (the same
    reasoning as ``GracefulShutdown.average_and_poll``)."""
    ok = jnp.all(jnp.isfinite(value)).astype(jnp.float32)
    for ax in axis_names:
        ok = jax.lax.pmin(ok, ax)
    return ok > 0


def guarded_update(tx, grads, opt_state, params, *, loss=None,
                   extra_ok=None, guard=True):
    """Optimizer update with a non-finite sentinel, traced-code safe.

    Computes the global grad norm and a finite flag (``isfinite(norm)``
    catches a NaN/Inf in any leaf — both propagate through the norm; a
    non-finite ``loss`` also trips it, as does ``extra_ok=False`` from a
    collective per-shard check).  When ``guard`` and the flag is down, the
    returned params/opt_state are the *inputs*, element-selected by
    ``jnp.where`` — apply_if_finite-style masking, so a poisoned step
    leaves the training state bitwise untouched (the skipped step does not
    advance the Adam count either).  Returns ``(params, opt_state,
    health)`` where ``health`` is a dict of f32 device scalars:
    ``loss``, ``grad_norm``, ``applied`` (1.0 applied / 0.0 skipped).
    """
    gnorm = optax.global_norm(grads)
    ok = jnp.isfinite(gnorm)
    if loss is not None:
        ok = jnp.logical_and(ok, jnp.isfinite(loss))
    if extra_ok is not None:
        ok = jnp.logical_and(ok, extra_ok)
    updates, new_opt = tx.update(grads, opt_state, params)
    new_params = optax.apply_updates(params, updates)
    if guard:
        new_params = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                                  new_params, params)
        new_opt = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                               new_opt, opt_state)
    health = {"loss": (jnp.asarray(loss, jnp.float32)
                       if loss is not None else jnp.float32(0.0)),
              "grad_norm": jnp.asarray(gnorm, jnp.float32),
              "applied": ok.astype(jnp.float32)}
    return new_params, new_opt, health


# --- host side: fault ports, anomaly policy, rollback, watchdog ----------


def fault_scale_for(step: int) -> float:
    """The loss-scale injection port for the health-enabled train steps:
    1.0 normally; NaN when ``grad_nan:at_step=step`` fires (the whole
    gradient tree goes non-finite on device — the sentinel must mask the
    update); :data:`SPIKE_SCALE` when ``loss_spike:at_step=step`` fires (a
    genuine finite spike whose poisoned update LANDS — the state a
    rollback must discard).  A plain float: it enters the step as a traced
    scalar argument, so injection never retraces."""
    if "at_step" in faults.fire("grad_nan", step=step):
        return float("nan")
    if "at_step" in faults.fire("loss_spike", step=step):
        return SPIKE_SCALE
    return 1.0


class RollbackAndSkip(Exception):
    """Raised by a trainer's step loop when the anomaly policy escalates:
    caught by :func:`run_with_rollback`, which relaunches the run with
    ``--resume auto`` (→ ``CheckpointManager.latest_valid()``), the data
    window up to ``step`` skipped, and the LR multiplied by
    ``lr_backoff``."""

    def __init__(self, step: int, max_rollbacks: int = 3,
                 lr_backoff: float = 0.5, reason: str = "anomaly"):
        super().__init__(f"rollback requested at step {step} ({reason})")
        self.step = int(step)
        self.max_rollbacks = int(max_rollbacks)
        self.lr_backoff = float(lr_backoff)
        self.reason = reason


def argv_with_resume_auto(argv, drop=("--resume", "--dalle_path",
                                      "--resume_path")):
    """Rebuild a trainer argv for a rollback relaunch: strip any explicit
    checkpoint/resume selection (they are mutually exclusive with
    ``--resume auto`` and would pin the run to a *pre*-rollback
    checkpoint) and append ``--resume auto``."""
    out = []
    skip_value = False
    for a in argv:
        if skip_value:
            skip_value = False
            continue
        if a in drop:
            skip_value = True
            continue
        if any(a.startswith(d + "=") for d in drop):
            continue
        out.append(a)
    return out + ["--resume", "auto"]


def run_with_rollback(run_fn, argv):
    """The rollback-and-skip escalation loop shared by both trainers.

    ``run_fn(argv, lr_scale=..., skip_past=...)`` is the real trainer main
    body; a :class:`RollbackAndSkip` escape relaunches it with ``--resume
    auto`` (latest valid managed checkpoint), the anomalous data window
    skipped, and a compounding LR backoff.  The budget rides in the
    exception (from the trainer's ``--max_rollbacks``); exhausting it
    exits with the documented ``ExitCode.ROLLBACK_BUDGET`` so supervisors
    know a relaunch will NOT help — this needs a human."""
    rollbacks = 0
    lr_scale = 1.0
    skip_past = None
    while True:
        try:
            return run_fn(argv, lr_scale=lr_scale, skip_past=skip_past)
        except RollbackAndSkip as rb:
            rollbacks += 1
            if rollbacks > rb.max_rollbacks:
                telemetry.note(
                    "health", "rollback_budget",
                    f"rollback budget exhausted ({rb.max_rollbacks}): "
                    f"aborting with exit code "
                    f"{int(ExitCode.ROLLBACK_BUDGET)} — automatic recovery "
                    "will not converge, a human must look at the anomaly "
                    "bundles", prefix="[guardrails]", step=rb.step)
                sys.exit(int(ExitCode.ROLLBACK_BUDGET))
            lr_scale *= rb.lr_backoff
            skip_past = rb.step
            argv = argv_with_resume_auto(argv)
            telemetry.note(
                "health", "rollback",
                f"rollback {rollbacks}/{rb.max_rollbacks} ({rb.reason} at "
                f"step {rb.step}): relaunching with --resume auto, skipping "
                f"data through step {rb.step}, lr x{lr_scale:g}",
                prefix="[guardrails]", step=rb.step, reason=rb.reason,
                rollbacks=rollbacks, lr_scale=lr_scale)


class HealthMonitor:
    """Host-side anomaly policy over the per-step health vectors.

    Keeps a rolling window of recent finite losses and classifies each
    observed step with a robust z-score — ``|loss - median| / (1.4826 *
    MAD)`` — plus an EMA trend for sustained divergence.  Median/MAD
    instead of mean/std because the statistic must survive the very
    outliers it exists to flag.  Verdicts: ``ok``, ``nonfinite`` (the
    device sentinel already skipped the update), ``spike`` (finite but
    z > ``spike_zscore``), ``diverged`` (EMA above ``divergence_factor``
    x its best for ``patience`` consecutive observations).

    ``mode`` maps verdicts to actions: ``warn`` logs only; ``skip`` logs
    and relies on the on-device masking; ``rollback`` additionally sets
    :attr:`wants_rollback` on spike / divergence / a ``nonfinite_patience``
    streak of skipped steps (one bad batch is masked for free — a *streak*
    means the data or the state is wrong and replay-from-checkpoint is the
    fix)."""

    def __init__(self, mode: str = "skip", spike_zscore: float = 8.0,
                 window: int = 64, warmup: int = 12,
                 nonfinite_patience: int = 3, patience: int = 5,
                 divergence_factor: float = 2.0, ema_alpha: float = 0.05):
        assert mode in ("warn", "skip", "rollback"), mode
        self.mode = mode
        self.spike_zscore = float(spike_zscore)
        self.warmup = int(warmup)
        self.nonfinite_patience = int(nonfinite_patience)
        self.patience = int(patience)
        self.divergence_factor = float(divergence_factor)
        self.ema_alpha = float(ema_alpha)
        self._losses = collections.deque(maxlen=int(window))
        self._ema = None
        self._best_ema = math.inf
        self._bad_trend = 0
        self._nonfinite_run = 0
        self.last_verdict = "ok"
        self.last_loss = None
        self.last_grad_norm = None
        self.last_step = None
        self.counts = collections.Counter()
        self.wants_rollback = False
        self.rollback_reason = None

    # -- statistics --

    def _zscore(self, loss: float) -> Optional[float]:
        if len(self._losses) < self.warmup:
            return None
        ordered = sorted(self._losses)
        median = ordered[len(ordered) // 2]
        mad = sorted(abs(v - median) for v in ordered)[len(ordered) // 2]
        # relative floor: a degenerate window (near-identical losses, MAD
        # ~ 0) must not turn a 0.1% wiggle into an infinite z-score — the
        # spike gate is for order-of-magnitude outliers, not float noise
        scale = max(1.4826 * mad, 1e-3 * abs(median), 1e-12)
        return abs(loss - median) / scale

    # -- observation --

    def observe(self, step: int, loss: float, grad_norm: float,
                applied: float) -> str:
        """Classify one step's health vector; returns the verdict and
        updates :attr:`wants_rollback` per the mode's policy."""
        self.last_step = int(step)
        self.last_loss = float(loss)
        self.last_grad_norm = float(grad_norm)
        verdict = "ok"
        if applied < 0.5 or not math.isfinite(loss):
            verdict = "nonfinite"
            self._nonfinite_run += 1
        else:
            self._nonfinite_run = 0
            z = self._zscore(loss)
            if z is not None and z > self.spike_zscore:
                verdict = "spike"
            else:
                # only sane losses feed the rolling statistic — a spike
                # must not drag the window toward itself
                self._losses.append(loss)
                self._ema = (loss if self._ema is None else
                             self.ema_alpha * loss
                             + (1 - self.ema_alpha) * self._ema)
                self._best_ema = min(self._best_ema, self._ema)
                if (len(self._losses) >= self.warmup and self._ema
                        > self.divergence_factor * self._best_ema):
                    self._bad_trend += 1
                    if self._bad_trend >= self.patience:
                        verdict = "diverged"
                else:
                    self._bad_trend = 0
        self.counts[verdict] += 1
        self.last_verdict = verdict
        if verdict != "ok":
            detail = {"nonfinite": "update skipped by the on-device "
                                   "sentinel (params/opt_state untouched)",
                      "spike": f"robust z > {self.spike_zscore:g}",
                      "diverged": f"loss EMA > {self.divergence_factor:g}x "
                                  "its best"}[verdict]
            telemetry.note(
                "health", verdict,
                f"step {step}: {verdict} — loss {loss:.6g} "
                f"grad_norm {grad_norm:.6g} ({detail})",
                prefix="[guardrails]", step=int(step), loss=float(loss),
                grad_norm=float(grad_norm))
        if self.mode == "rollback" and not self.wants_rollback:
            if verdict in ("spike", "diverged"):
                self.wants_rollback = True
                self.rollback_reason = verdict
            elif self._nonfinite_run >= self.nonfinite_patience:
                self.wants_rollback = True
                self.rollback_reason = (
                    f"{self._nonfinite_run} consecutive non-finite steps")
            if self.wants_rollback:
                telemetry.emit("health", "rollback_wanted", step=int(step),
                               reason=self.rollback_reason)
        return verdict

    # -- consumers --

    def beat_extras(self) -> dict:
        """Health fields for ``Heartbeat.beat(**extra)`` so an external
        monitor sees sickness without reading logs."""
        out = {"health_state": self.last_verdict}
        if self.last_loss is not None:
            out["loss"] = self.last_loss
        if self.last_grad_norm is not None:
            out["grad_norm"] = self.last_grad_norm
        return out

    def history(self) -> list:
        return list(self._losses)


def write_anomaly_bundle(directory, step: int, report: dict) -> Path:
    """Post-mortem record of an escalation: ``anomaly-{step:08d}/`` with a
    ``report.json`` (loss history, batch window, rng, config fingerprint —
    whatever the trainer hands over), published by atomic directory rename
    so a crash mid-write can never leave a half-bundle that looks whole.
    Idempotent per step (a collective escalation writes once)."""
    directory = Path(directory)
    final = directory / f"anomaly-{int(step):08d}"
    if final.exists():
        return final
    directory.mkdir(parents=True, exist_ok=True)
    tmp = Path(tempfile.mkdtemp(dir=directory, prefix=".anomaly-"))
    try:
        with open(tmp / "report.json", "w") as f:
            json.dump(dict(report, step=int(step), time=time.time()), f,
                      indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    except BaseException:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise
    telemetry.note("health", "anomaly_bundle",
                   f"anomaly bundle written to {final}",
                   prefix="[guardrails]", step=int(step), path=str(final))
    return final


class StepWatchdog:
    """Hung-step watchdog: a monotonic-clock thread armed around each
    device step.  A wedged device call raises no exception — the loop just
    never returns (DESIGN.md §6) — so past the deadline the watchdog dumps
    every thread's stack (the post-mortem: *where* it wedged) and exits
    the process with ``ExitCode.WEDGED``, which the supervisors treat as
    restart-with-resume.

    The first :meth:`arm` call is a free pass: step 1 includes the XLA
    compile (minutes at real sizes), which must not read as a wedge —
    the same reasoning as ``Heartbeat``'s None-until-first-beat.  Exit is
    ``os._exit`` because the main thread is, by definition, stuck inside
    a call that will never return; ``on_expire`` exists for tests."""

    def __init__(self, deadline: float, on_expire=None,
                 poll: Optional[float] = None):
        self.deadline = float(deadline)
        self._on_expire = on_expire
        self._armed_at: Optional[float] = None
        self._step: Optional[int] = None
        self._first_pass = True
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._watch, name="step-watchdog", daemon=True)
        self._poll = poll if poll is not None else min(self.deadline / 4, 1.0)
        self._thread.start()

    def arm(self, step: int) -> None:
        if self._first_pass:  # step 1 == XLA compile, not a wedge
            self._first_pass = False
            return
        self._step = int(step)
        self._armed_at = time.monotonic()

    def disarm(self) -> None:
        self._armed_at = None

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _watch(self) -> None:
        while not self._stop.wait(max(self._poll, 0.01)):
            armed_at = self._armed_at
            if armed_at is None:
                continue
            age = time.monotonic() - armed_at
            if age > self.deadline:
                self._expire(age)
                return

    def _expire(self, age: float) -> None:
        # emitted (and os.write-flushed) BEFORE the stack dump + _exit, so
        # the stream's last record names the wedged step
        telemetry.note(
            "health", "watchdog_expired",
            f"hung step: step {self._step} exceeded the "
            f"{self.deadline:g}s deadline ({age:.0f}s) — a wedged device "
            f"call or collective.  Dumping all thread stacks and exiting "
            f"{int(ExitCode.WEDGED)} (supervisors relaunch with "
            "--resume auto).", prefix="[guardrails]", step=self._step,
            age_s=age, deadline_s=self.deadline)
        if self._on_expire is not None:
            self._on_expire()
            return
        import faulthandler

        faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        sys.stderr.flush()
        os._exit(int(ExitCode.WEDGED))
