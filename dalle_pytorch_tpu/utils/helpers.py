"""Small shared helpers.

TPU-native analog of the helper block in the reference
(`/root/reference/dalle_pytorch/dalle_pytorch.py:13-50`), re-expressed for a
functional JAX codebase: no in-place ops, no `.training` flags, explicit RNG.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def env_flag(name: str, default: bool = False) -> bool:
    """Boolean environment flag with OFF-able semantics: unset -> default;
    ``"0"``, ``"false"``, ``"no"``, ``"off"`` and the empty string (any
    case) -> False; anything else -> True.

    ``bool(os.environ.get(X))`` treats ``X=0`` as ON — an operator
    disabling a flag with 0 would silently enable it (the BENCH_PALLAS /
    GRAFT_DRYRUN_FULL footgun, ADVICE.md round 5).  All boolean env knobs
    parse through here.
    """
    import os

    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() not in ("", "0", "false", "no", "off")


def atomic_write_bytes(path, data: bytes) -> None:
    """Write ``data`` whole-or-not-at-all: temp file + fsync +
    ``os.replace`` (the I1 discipline of DESIGN.md §8 — a reader can see
    the old file or the new file, never a torn one).  Durable-state writes
    outside ``utils/`` must route through here or the checkpoint helpers
    (graftlint CKPT001)."""
    import os
    import tempfile
    from pathlib import Path

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                               prefix=f".{path.name}-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def atomic_write_json(path, obj, indent: int = 1) -> None:
    """:func:`atomic_write_bytes` of a JSON document."""
    import json

    atomic_write_bytes(path, json.dumps(obj, indent=indent).encode())


def exists(val):
    return val is not None


def default(val, d):
    if val is not None:
        return val
    return d() if callable(d) else d


def cast_tuple(val, depth: int = 1):
    if isinstance(val, list):
        val = tuple(val)
    return val if isinstance(val, tuple) else (val,) * depth


def max_neg_value(dtype) -> float:
    """Most-negative finite value for a dtype (ref dalle_pytorch.py:483)."""
    return -jnp.finfo(dtype).max


def masked_mean(t: jax.Array, mask: jax.Array, axis: int = 1) -> jax.Array:
    """Mean over `axis` counting only positions where `mask` is True.

    Ref `dalle_pytorch.py:29-31` (CLIP text pooling).
    """
    mask = mask[..., None]
    t = jnp.where(mask, t, 0.0)
    return t.sum(axis=axis) / mask.sum(axis=axis)


def l2norm(t: jax.Array, axis: int = -1, eps: float = 1e-12) -> jax.Array:
    return t / jnp.maximum(jnp.linalg.norm(t, axis=axis, keepdims=True), eps)


def top_k_filter(logits: jax.Array, thres: float = 0.5,
                 k_vocab: Optional[int] = None) -> jax.Array:
    """Keep the top `max(int((1-thres)*V), 1)` logits, set the rest to -inf.

    Exact semantics of the reference sampler filter
    (`dalle_pytorch.py:44-50`): k is derived from the vocab size, not given
    directly. Static `k` keeps this jit-friendly.

    `k_vocab` overrides the vocab size V used to derive k: the decode path
    hands in image-vocab-only logits (the text half of the joint vocab is
    structurally -inf there and is never materialized), but the reference
    derives k from the FULL joint vocab — since its -inf text entries can
    never win a top-k slot anyway, deriving k from the full size over the
    sliced logits selects the identical candidate set.
    """
    num_logits = k_vocab if k_vocab is not None else logits.shape[-1]
    k = max(int((1 - thres) * num_logits), 1)
    k = min(k, logits.shape[-1])
    vals, _ = jax.lax.top_k(logits, k)
    kth = vals[..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def top_p_filter(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filtering (beyond the reference, which only has top-k): keep
    the smallest set of tokens whose softmax mass reaches ``p``, set the
    rest to -inf.  The highest-probability token always survives.  Static
    shapes throughout — jit/scan friendly."""
    assert 0.0 < p <= 1.0, f"top_p must be in (0, 1], got {p}"
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i survives if the mass BEFORE it is < p (so the first token that
    # crosses p is still included)
    keep = (cum - probs) < p
    # threshold = smallest surviving logit; everything below is cut
    cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits < cutoff, -jnp.inf, logits)
