"""Ulysses attention — all-to-all sequence/context parallelism.

The second of the framework's two exact sequence-parallel schemes (the
first, k/v-rotation ring attention, lives in ``parallel/ring.py``).  The
reference has no long-context machinery at all (SURVEY.md §5.7); on TPU we
treat the sequence as a shardable axis and let the user pick the scheme
that matches their mesh:

* **ring** — O(sp) neighbor `ppermute` hops; bandwidth rides the ICI ring,
  per-device memory O(n_local²).  Best when `sp` is large and heads are few.
* **ulysses** (this module, after DeepSpeed-Ulysses, arXiv:2309.14509) —
  two `all_to_all` collectives re-shard the *sequence* axis into the *head*
  axis, so each device computes full-sequence attention for `h / sp` heads,
  then the inverse all-to-all restores sequence sharding.  Communication is
  O(1) collectives per layer regardless of `sp`; requires ``heads % sp ==
  0``.  Best when heads are plentiful (h >= sp) and the per-device full
  [n, n] score tile fits, i.e. moderate n scaled over many heads.

Both schemes are exact (bitwise-independent of `sp` up to float
reassociation), differentiable (all_to_all's transpose is the inverse
all_to_all), and reuse the same `AttnPattern` predicate as every other
attention in the framework, so the DALLE variants (full / axial / conv_like
/ sparse) all run sequence-parallel.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import AttnPattern
from .mesh import shard_map
from .ring import NEG_INF, _chunk_mask


def ulysses_attention(q, k, v, *, axis_name: str,
                      pattern: Optional[AttnPattern] = None,
                      causal: bool = True) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis_name`` via
    head<->sequence all-to-all re-sharding.

    q/k/v: local shards [b, h, n_local, dh] (full heads, 1/sp of the
    sequence, contiguous chunks ordered by axis index).  Returns the local
    output shard [b, h, n_local, dh].  Requires ``h % sp == 0``.
    """
    sp = jax.lax.psum(1, axis_name)
    b, h, nl, dh = q.shape
    assert h % sp == 0 if isinstance(sp, int) else True, (
        f"ulysses needs heads ({h}) divisible by the sp axis size")
    scale = dh ** -0.5
    layout = None
    if pattern is not None and pattern.variant == "sparse":
        layout = jnp.asarray(pattern.block_layout())

    # one collective in: [3, b, h, n_local, dh] -> [3, b, h/sp, n, dh]
    # (scatter heads, gather sequence)
    qg, kg, vg = jax.lax.all_to_all(
        jnp.stack([q, k, v]), axis_name, split_axis=2, concat_axis=3,
        tiled=True)
    n = qg.shape[2]

    s = jnp.einsum("bhid,bhjd->bhij", qg.astype(jnp.float32) * scale,
                   kg.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    allow = _chunk_mask(pattern, causal, 0, 0, n, n, layout=layout)
    s = jnp.where(allow[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(allow[None, None], p, 0.0)  # fully-masked rows -> 0
    out = jnp.einsum("bhij,bhjd->bhid", p, vg.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    # one collective out: split the sequence back, gather heads
    return jax.lax.all_to_all(out.astype(q.dtype), axis_name, split_axis=2,
                              concat_axis=1, tiled=True)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, *, sp_axis: str = "sp",
                              dp_axis: Optional[str] = "dp",
                              pattern: Optional[AttnPattern] = None,
                              causal: bool = True) -> jax.Array:
    """Standalone wrapper: q/k/v are global [b, h, n, dh]; the sequence dim
    is sharded over `sp_axis` (and batch over `dp_axis` if present)."""
    dp = dp_axis if dp_axis and dp_axis in mesh.axis_names else None
    spec = P(dp, None, sp_axis, None)

    fn = partial(ulysses_attention, axis_name=sp_axis, pattern=pattern,
                 causal=causal)
    sharded = shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return sharded(q, k, v)
