"""ParallelPlan — the ONE declarative source of the sharding contract.

Before this module, the mapping "mesh axes + partition rules -> shardings"
lived in three hand-kept places: ``Partitioner.param_shardings`` applied at
init, the same shardings rebuilt at checkpoint restore, and the
``training._pin_update_shardings`` constraint pinning the step outputs —
plus a fourth copy in ``tools/spmd_check.py``'s per-plan expectation table.
Each copy could drift silently (the ROADMAP "sharding-spec drift" hazard).
A :class:`ParallelPlan` replaces all of them: one frozen object holding the
mesh axis sizes and the regex rule table, from which every consumer
*derives* —

* ``plan.make_mesh()`` / ``plan.partitioner()`` build the run's mesh and
  :class:`~dalle_pytorch_tpu.parallel.mesh.Partitioner` (init shardings,
  restore templates, and the update-output pin all read the SAME
  partitioner, so they cannot disagree);
* ``plan.config_overrides()`` is the model-config half of the contract
  (``ring_axis``/``sp_impl``/``sp_size`` for the sequence-parallel plans)
  that ``tools/spmd_check.py`` and the trainers previously each spelled
  out by hand;
* ``plan.to_manifest()`` is what :class:`CheckpointManager` records in
  every checkpoint manifest, so a resume can *say* which plan + topology
  wrote the checkpoint it is resharding from (elastic resume);
* :data:`PLAN_REGISTRY` names the six canonical plans (dp / fsdp / tp /
  sp-ring / sp-ulysses / pp) the analysis suite gates — spmd_check's
  matrix is generated from this registry, not maintained beside it.

Plan specs (``ParallelPlan.parse``) are dot-separated axis tokens::

    dp            # pure data parallel, dp absorbs every device
    dp2.tp4       # 2-way data x 4-way tensor parallel
    fsdp4         # 4-way ZeRO-style parameter sharding (dp absorbs rest)
    sp-ring2      # 2-way ring sequence parallelism
    sp-ulysses2   # 2-way Ulysses (head<->sequence all-to-all)
    pp2           # 2-stage GPipe pipeline
    dcn2.fsdp2    # 2 slices over DCN x 2-way fsdp inside each

or one of the registry names above.  The partition rule table itself
(:data:`PARTITION_RULES`, the dalle-mini-style regex -> PartitionSpec map,
SNIPPETS [1]) lives here too; ``mesh.DEFAULT_RULES`` re-exports it.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Optional, Tuple

from jax.sharding import PartitionSpec as P

# Default partition rules for our models' flax param trees.  Matched against
# the '/'-joined param path; first hit wins; default = replicated.
# Dense kernels are [d_in, d_out]; embeddings are [vocab, dim].
PARTITION_RULES: Tuple[Tuple[str, P], ...] = (
    # fused QKV [dim, 3, heads, dh]: fsdp on features, tp on heads
    (r".*to_qkv/kernel$", P("fsdp", None, "tp", None)),
    # column-parallel projections (split output features over tp)
    (r".*(to_q|to_k|to_v)/kernel$", P("fsdp", "tp")),
    (r".*ff/dense_in/kernel$", P("fsdp", "tp")),
    # row-parallel projections (split input features over tp)
    (r".*to_out/kernel$", P("tp", "fsdp")),
    (r".*ff/dense_out/kernel$", P("tp", "fsdp")),
    # token embeddings: vocab over fsdp (the big dim — ZeRO memory win),
    # features over tp (matches the logits head's tp-sharded vocab).  NOT
    # P("tp","fsdp"): features-over-fsdp makes the embedding-gradient
    # scatter reshard its cotangent from batch-sharded to fsdp-on-features
    # with a tile permutation GSPMD can only do by full rematerialization
    # ("Involuntary full rematerialization" per step, wasted ICI bandwidth)
    (r".*(text_emb|image_emb)/embedding$", P("fsdp", "tp")),
    # per-phase head kernels (PhaseLogits): each phase tp-shards its OWN
    # vocab dim, so the phase boundary is a param boundary — the sliced
    # head works under tp with no interior-slice resharding
    # graftspec's shallow-exit draft head is SELF-speculative: it re-uses
    # these exact head params after spec_draft_depth blocks (no draft-only
    # kernels exist), so spec_decode adds no partition rules — the plan
    # fields ride DALLEConfig._PLAN_FIELDS for fingerprinting only
    (r".*to_logits_dense/(text_kernel|image_kernel)$", P("fsdp", "tp")),
    (r".*to_logits_dense/(text_bias|image_bias)$", P("tp")),
    # conv kernels (VAE): shard output channels over fsdp only
    (r".*codebook/embedding$", P(None, "fsdp")),
    (r".*/kernel$", P(None, None)),
)

_TOKEN_RE = re.compile(
    r"^(?P<axis>dp|fsdp|tp|pp|ep|dcn|sp-ring|sp-ulysses|sp)(?P<n>\d*)$")


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """One parallelism plan: mesh axis sizes + the partition rule table.

    ``dp=None`` means the data axis absorbs every device the other axes
    don't claim (so one spec string serves any device count — the elastic
    half of elastic resume).  ``rules`` is the regex table the Partitioner
    compiles; it is part of the plan so a run with custom rules records
    *that* contract in its manifests too.
    """

    name: str
    dp: Optional[int] = None
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    dcn_dp: int = 1
    sp_impl: Optional[str] = None  # 'ring' | 'ulysses' when sp > 1
    rules: Tuple[Tuple[str, P], ...] = PARTITION_RULES

    def __post_init__(self):
        if self.sp > 1 and self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"plan {self.name!r}: sp={self.sp} needs sp_impl "
                "'ring' or 'ulysses'")
        if (self.sp > 1) + (self.pp > 1) + (self.ep > 1) > 1:
            raise ValueError(
                f"plan {self.name!r}: sp/pp/ep are mutually exclusive")
        if (self.sp > 1 or self.pp > 1 or self.ep > 1) and (
                self.fsdp > 1 or self.tp > 1 or self.dcn_dp > 1):
            raise ValueError(
                f"plan {self.name!r}: sp/pp/ep own the inner mesh axis; "
                "they cannot combine with fsdp/tp/dcn_dp")

    # --- derivation: every consumer reads these, none keeps a copy --------

    def mesh_kwargs(self) -> dict:
        """Keyword args for :func:`mesh.make_mesh` — the mesh half of the
        contract (what spmd_check's hand-kept PLANS table used to spell)."""
        out = {}
        if self.dp is not None:
            out["dp"] = self.dp
        for key in ("fsdp", "tp", "sp", "pp", "ep", "dcn_dp"):
            val = getattr(self, key)
            if val != 1:
                out[key] = val
        return out

    def make_mesh(self, devices=None):
        from .mesh import make_mesh

        return make_mesh(devices=devices, **self.mesh_kwargs())

    def partitioner(self, devices=None, mesh=None):
        """The run's Partitioner, built FROM this plan: init shardings,
        checkpoint-restore templates, and the step-output pin all derive
        from the one object returned here."""
        from .mesh import Partitioner

        return Partitioner(mesh=mesh if mesh is not None
                           else self.make_mesh(devices), plan=self)

    def config_overrides(self) -> dict:
        """The model-config (DALLEConfig) half of the plan — the execution
        strategy is per-run, never stored in checkpoints."""
        if self.sp > 1:
            return dict(ring_axis="sp", sp_impl=self.sp_impl,
                        sp_size=self.sp)
        return {}

    # --- identity / serialization -----------------------------------------

    def spec(self) -> str:
        """Canonical spec string (``ParallelPlan.parse`` round-trips it)."""
        parts = []
        if self.dp is not None:
            parts.append(f"dp{self.dp}")
        if self.dcn_dp > 1:
            parts.append(f"dcn{self.dcn_dp}")
        for key in ("fsdp", "tp", "pp", "ep"):
            if getattr(self, key) > 1:
                parts.append(f"{key}{getattr(self, key)}")
        if self.sp > 1:
            parts.append(f"sp-{self.sp_impl}{self.sp}")
        return ".".join(parts) or "dp"

    def to_manifest(self) -> dict:
        """The checkpoint-manifest record of this plan: enough for a later
        resume (possibly on different hardware) to know exactly what wrote
        the checkpoint.  Rules ride as their pattern strings — the specs
        are derivable, the identity check is what matters."""
        return {
            "name": self.name,
            "spec": self.spec(),
            "axes": {k: getattr(self, k) for k in
                     ("dp", "fsdp", "tp", "sp", "pp", "ep", "dcn_dp")},
            "sp_impl": self.sp_impl,
            "rule_patterns": [pat for pat, _ in self.rules],
        }

    @classmethod
    def from_manifest(cls, rec: dict) -> "ParallelPlan":
        """Rebuild a plan identity from a manifest record (rules fall back
        to the current table: the patterns in the record are the written
        run's identity, not restorable PartitionSpecs)."""
        axes = dict(rec.get("axes") or {})
        return cls(name=str(rec.get("name", rec.get("spec", "dp"))),
                   dp=axes.get("dp"),
                   fsdp=int(axes.get("fsdp", 1)), tp=int(axes.get("tp", 1)),
                   sp=int(axes.get("sp", 1)), pp=int(axes.get("pp", 1)),
                   ep=int(axes.get("ep", 1)),
                   dcn_dp=int(axes.get("dcn_dp", 1)),
                   sp_impl=rec.get("sp_impl"))

    @classmethod
    def parse(cls, spec: str) -> "ParallelPlan":
        """Parse a CLI plan spec: a registry name or dot-separated axis
        tokens (module docstring grammar)."""
        spec = (spec or "").strip()
        if spec in PLAN_REGISTRY:
            return PLAN_REGISTRY[spec]
        kwargs: dict = {}
        sp_impl = None
        for token in filter(None, spec.split(".")):
            m = _TOKEN_RE.match(token)
            if not m:
                raise ValueError(
                    f"bad plan token {token!r} in {spec!r}: expected "
                    "axis tokens like dp2, fsdp4, tp2, sp-ring2, pp2, dcn2 "
                    f"or a registry name ({', '.join(sorted(PLAN_REGISTRY))})")
            axis, n = m.group("axis"), m.group("n")
            size = int(n) if n else None
            if axis == "dp":
                kwargs["dp"] = size  # dp with no count = absorb
                continue
            if size is None:
                raise ValueError(
                    f"bad plan token {token!r} in {spec!r}: every axis but "
                    "dp needs an explicit way count")
            if axis.startswith("sp"):
                if axis == "sp":
                    raise ValueError(
                        f"bad plan token {token!r} in {spec!r}: sequence "
                        "parallelism must name its scheme (sp-ring2 or "
                        "sp-ulysses2)")
                sp_impl = axis.split("-", 1)[1]
                axis = "sp"
            if axis == "dcn":
                axis = "dcn_dp"
            if axis in kwargs and axis != "dp":
                raise ValueError(f"duplicate axis {axis!r} in plan {spec!r}")
            kwargs[axis] = size
        return cls(name=spec or "dp", sp_impl=sp_impl, **kwargs)

    @classmethod
    def from_mesh_flags(cls, *, fsdp: int = 1, tp: int = 1, dcn_dp: int = 1,
                        sp: int = 1, sp_impl: Optional[str] = None,
                        pp: int = 1) -> "ParallelPlan":
        """The legacy CLI surface (--mesh_fsdp/--mesh_tp/--mesh_dcn_dp/
        --mesh_sp/--pipeline_stages) expressed as a plan — so runs without
        --plan still record a faithful plan identity in their manifests."""
        plan = cls(name="flags", fsdp=int(fsdp), tp=int(tp),
                   dcn_dp=int(dcn_dp), sp=int(sp),
                   sp_impl=sp_impl if int(sp) > 1 else None, pp=int(pp))
        return dataclasses.replace(plan, name=plan.spec())


# The six canonical plans the analysis suite gates (sized for the 8-device
# virtual test mesh; dp absorbs the remainder on any larger topology).
# tools/spmd_check.py generates its per-plan matrix FROM this registry —
# a new plan here is automatically traced, or loudly missing a harness.
# Scale-preset entries (presets.SCALE_PRESETS, e.g. cub-512) pair a plan
# with a scaled config geometry; spmd_check excludes them from the
# per-push matrix and proves their S4 budget under ``--presets``.
PLAN_REGISTRY = {
    "dp": ParallelPlan("dp"),
    "fsdp": ParallelPlan("fsdp", fsdp=4),
    "tp": ParallelPlan("tp", tp=2),
    "sp-ring": ParallelPlan("sp-ring", sp=2, sp_impl="ring"),
    "sp-ulysses": ParallelPlan("sp-ulysses", sp=2, sp_impl="ulysses"),
    "pp": ParallelPlan("pp", pp=2),
    # the dim-512 scale rung: ZeRO param sharding is what makes ~345M fit
    # a 16 GiB chip at all (presets.cub512_config is the geometry half)
    "cub-512": ParallelPlan("cub-512", fsdp=4),
    # the dim-1024 MFU rung (~1.3B, presets.cub1024_config): the fsdp x tp
    # hybrid — all 8 ways go to state sharding, none to dp, and splitting
    # features over tp on top of fsdp keeps the per-device all-gather
    # working set below pure fsdp-8's (tools/plan_search.py's chip-free
    # sweep scores this cell against the alternatives, dcn variants
    # included, and PLAN_LEDGER.json pins the winner per topology)
    "cub-1024": ParallelPlan("cub-1024", fsdp=4, tp=2),
}


def resolve_plan_args(args) -> ParallelPlan:
    """Resolve the run's plan — ``--plan`` wins, else the legacy mesh
    flags — and write the resolved axis sizes back onto ``args`` so every
    downstream flag consumer (mesh construction, sp/pp mode selection,
    flag validation) reads ONE contract.  Trainers call this right after
    ``parse_args``; the returned plan is what the CheckpointManager
    records in manifests."""
    spec = getattr(args, "plan", None)
    if not spec:
        return ParallelPlan.from_mesh_flags(
            fsdp=getattr(args, "mesh_fsdp", 1),
            tp=getattr(args, "mesh_tp", 1),
            dcn_dp=getattr(args, "mesh_dcn_dp", 1),
            sp=getattr(args, "mesh_sp", 1),
            sp_impl=getattr(args, "sp_impl", None),
            pp=getattr(args, "pipeline_stages", 1))
    plan = ParallelPlan.parse(spec)
    if plan.ep > 1:
        raise ValueError("--plan with an ep axis is not supported by the "
                         "trainers (MoE expert sharding is a model-config "
                         "concern, see ops/moe.py)")
    if plan.sp > 1 and not hasattr(args, "mesh_sp"):
        raise ValueError(f"--plan {spec}: this trainer has no sequence-"
                         "parallel path")
    if plan.pp > 1 and not hasattr(args, "pipeline_stages"):
        raise ValueError(f"--plan {spec}: this trainer has no pipeline-"
                         "parallel path")
    args.mesh_fsdp, args.mesh_tp = plan.fsdp, plan.tp
    args.mesh_dcn_dp = plan.dcn_dp
    if hasattr(args, "mesh_sp"):
        args.mesh_sp = plan.sp
        if plan.sp > 1 and plan.sp_impl:
            args.sp_impl = plan.sp_impl
    if hasattr(args, "pipeline_stages"):
        args.pipeline_stages = plan.pp
    return plan


def current_topology() -> dict:
    """The topology half of a checkpoint manifest's provenance record:
    what hardware this process is actually running on right now."""
    import jax

    return {"device_count": jax.device_count(),
            "process_count": jax.process_count(),
            "platform": jax.default_backend()}


def describe_transition(written: Optional[dict], run_plan: "ParallelPlan",
                        topology: Optional[dict] = None) -> Optional[str]:
    """One operator line describing a cross-topology resume, or None when
    the checkpoint was written under this exact plan + topology (nothing
    to reshard).  ``written`` is the manifest's ``plan`` record."""
    if not written:
        return None
    topo_now = current_topology()
    same_plan = written.get("spec") == run_plan.spec()
    same_topo = (topology is None
                 or (topology.get("device_count") == topo_now["device_count"]
                     and topology.get("process_count")
                     == topo_now["process_count"]))
    if same_plan and same_topo:
        return None
    wrote = written.get("spec", "?")
    wrote_dev = (topology or {}).get("device_count", "?")
    return (f"elastic resume: checkpoint written under plan {wrote} "
            f"({wrote_dev} devices); resharding onto plan {run_plan.spec()} "
            f"({topo_now['device_count']} devices)")
