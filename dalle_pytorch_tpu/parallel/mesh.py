"""Device mesh + sharding utilities (the GSPMD heart of the framework).

The reference scales with NCCL data parallelism only (SURVEY.md §2.2:
DeepSpeed engine allreduce, Horovod DistributedOptimizer).  TPU-natively all
of that collapses into: build a `jax.sharding.Mesh`, annotate shardings, and
let XLA insert the collectives over ICI/DCN.  This module owns:

* mesh construction with named axes ``('dp', 'fsdp', 'tp')`` — data,
  fully-sharded-data (ZeRO-ish), tensor parallel;
* regex partition rules mapping flax param paths -> `PartitionSpec` (pattern
  after dalle-mini-style partitioning, see SNIPPETS.md [1]);
* global batch construction from per-process host arrays
  (`jax.make_array_from_process_local_data`) — the analog of torch's
  ``DistributedSampler`` + ``.cuda()`` H2D step.
"""
from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    _shard_map_impl = jax.shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
except AttributeError:  # jax <= 0.4.x: only the experimental entry point
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _SHARD_MAP_CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    The top-level ``jax.shard_map`` (and its ``check_vma`` kwarg) only
    exists on newer jax; 0.4.x ships it as
    ``jax.experimental.shard_map.shard_map`` with the same semantics under
    the ``check_rep`` name.  Every shard_map in the repo goes through here
    so the sp/pp training paths work on both."""
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs,
                           **{_SHARD_MAP_CHECK_KW: check_vma})

# The partition rule table lives on the declarative plan (plan.py is the
# single source of the sharding contract); this name survives for the many
# existing call sites that read it from here.
from .plan import PARTITION_RULES as DEFAULT_RULES  # noqa: E402,F401


def make_mesh(dp: Optional[int] = None, fsdp: int = 1, tp: int = 1,
              devices=None, dcn_dp: int = 1, sp: int = 1, pp: int = 1,
              ep: int = 1) -> Mesh:
    """Build a ('dp','fsdp','tp') mesh.  `dp=None` absorbs remaining devices.

    ``dcn_dp > 1`` targets multi-slice topologies (TPU pods joined over the
    data-center network): the ``dp`` axis is laid out so its outer ``dcn_dp``
    groups are whole slices — data-parallel gradient ``psum``s hierarchically
    reduce inside each slice over ICI first and only the per-slice partials
    cross DCN, while fsdp/tp collectives stay entirely on ICI.  ``dp`` counts
    the *total* data-parallel ways (ICI ways x dcn_dp).

    ``sp > 1`` / ``pp > 1`` / ``ep > 1`` instead build a ('dp','sp') /
    ('dp','pp') / ('dp','ep') mesh for sequence-parallel (ring/Ulysses
    shard_map), pipeline-parallel (GPipe shard_map), or expert-parallel
    (ep-sharded MoE kernels, ops/moe.py::ep_shard_moe_params) training —
    those strategies own their inner axis, so they are mutually exclusive
    with each other and with fsdp/tp/dcn_dp in one mesh.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if sp > 1 or pp > 1 or ep > 1:
        inner_name, inner = (("sp", sp) if sp > 1 else
                             ("pp", pp) if pp > 1 else ("ep", ep))
        assert (sp > 1) + (pp > 1) + (ep > 1) == 1, (
            "sp, pp and ep are mutually exclusive")
        assert fsdp == 1 and tp == 1 and dcn_dp == 1, (
            f"{inner_name} cannot be combined with fsdp/tp/dcn_dp in one mesh")
        assert n % inner == 0, f"{n} devices not divisible by {inner_name}={inner}"
        if dp is None:
            dp = n // inner
        assert dp * inner == n, f"mesh {dp}x{inner} != {n} devices"
        return Mesh(np.asarray(devices).reshape(dp, inner), ("dp", inner_name))
    if dp is None:
        assert n % (fsdp * tp) == 0, f"{n} devices not divisible by fsdp*tp={fsdp * tp}"
        dp = n // (fsdp * tp)
    assert dp * fsdp * tp == n, f"mesh {dp}x{fsdp}x{tp} != {n} devices"
    dev_array = np.asarray(devices).reshape(dp, fsdp, tp)
    if dcn_dp > 1:
        assert dp % dcn_dp == 0, f"dp={dp} not divisible by dcn_dp={dcn_dp}"
        slice_ids = {getattr(d, "slice_index", None) for d in devices}
        if None not in slice_ids and len(slice_ids) > 1:
            from jax.experimental import mesh_utils

            # genuine multi-slice topology: let shape/topology mismatches
            # raise — silently falling back would break the slice-local ICI
            # reduction guarantee that is the whole point of dcn_dp
            dev_array = mesh_utils.create_hybrid_device_mesh(
                (dp // dcn_dp, fsdp, tp), (dcn_dp, 1, 1), devices=devices)
        # else: no slice topology (CPU meshes in tests, single slice) —
        # row-major order already groups contiguous devices on the outer dp
        # axis, which is the right fallback layout
    return Mesh(dev_array, ("dp", "fsdp", "tp"))


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts)


def _prune_spec(spec: P, mesh: Mesh, shape) -> P:
    """Drop axes of size 1, axes absent from the mesh (sp/pp meshes carry
    no fsdp/tp), and axes that don't divide the dim — keeps rules valid on
    any mesh (e.g. pure-dp) without per-mesh rule sets."""
    out = []
    for dim, names in enumerate(spec):
        if names is None:
            out.append(None)
            continue
        names_t = (names,) if isinstance(names, str) else tuple(names)
        size = 1
        for nm in names_t:
            size *= mesh.shape.get(nm, 1)
        missing = any(nm not in mesh.shape for nm in names_t)
        if missing or size == 1 or dim >= len(shape) or shape[dim] % size != 0:
            out.append(None)
        else:
            out.append(names if isinstance(names, str) else names_t)
    return P(*out)


class Partitioner:
    """Owns the mesh + param/batch shardings for a training run.

    Built from a :class:`~dalle_pytorch_tpu.parallel.plan.ParallelPlan`
    (``plan.partitioner()`` / ``Partitioner(plan=...)``), which is the
    single source of the mesh axes and rule table — init shardings,
    checkpoint-restore templates (:meth:`opt_state_templates`), and the
    step-output pin (``training._pin_update_shardings``) all read THIS
    object, so the three former hand-kept copies cannot drift."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 rules: Optional[Sequence[Tuple[str, P]]] = None,
                 batch_axes=("dp", "fsdp"), plan=None):
        if rules is None:
            rules = plan.rules if plan is not None else DEFAULT_RULES
        self.plan = plan
        if mesh is None:
            mesh = plan.make_mesh() if plan is not None else make_mesh()
        self.mesh = mesh
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        # drop batch axes the mesh doesn't have (sp/pp meshes carry no fsdp)
        self.batch_axes = tuple(a for a in batch_axes if a in self.mesh.shape)
        self.batch_spec = P(self.batch_axes)
        self.data_sharding = NamedSharding(self.mesh, self.batch_spec)
        self.repl_sharding = NamedSharding(self.mesh, P())

    def spec_for(self, path, value) -> P:
        s = _path_str(path)
        for pat, spec in self.rules:
            if pat.match(s):
                return _prune_spec(spec, self.mesh, value.shape)
        return P()

    def param_specs(self, params):
        return jax.tree_util.tree_map_with_path(
            lambda p, v: self.spec_for(p, v), params
        )

    def param_shardings(self, params):
        return jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self.param_specs(params),
            is_leaf=lambda x: isinstance(x, P),
        )

    def shard_params(self, params):
        return jax.device_put(params, self.param_shardings(params))

    def init_opt_state(self, tx, params):
        """Fresh optimizer state with the Adam moments sharded like their
        params (the path rules match the ``mu``/``nu`` subtrees too — their
        leaf paths end in the same param names); scalar leaves (count,
        injected lr) fall through to replicated.  Without explicit
        out_shardings GSPMD is free to pick arbitrary moment layouts, which
        shows up as involuntary-rematerialization resharding in the update
        step."""
        sds = jax.eval_shape(tx.init, params)
        return jax.jit(tx.init, out_shardings=self.param_shardings(sds))(params)

    def opt_state_templates(self, opt_state) -> list:
        """Flat leaves of ``opt_state`` as ShapeDtypeStructs carrying THIS
        run's opt-state shardings — the restore targets for an elastic
        sharded-checkpoint load.  Single source of the opt-state sharding
        contract: a state restored through these lands on exactly the
        layout ``init_opt_state`` would have produced fresh."""
        return [
            jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=s)
            for t, s in zip(jax.tree.leaves(opt_state),
                            jax.tree.leaves(self.param_shardings(opt_state)))]

    def replicate(self, tree):
        return jax.device_put(tree, self.repl_sharding)

    def shard_batch(self, batch):
        """Per-process numpy batch -> globally sharded jax.Array.

        Under multi-process JAX each host holds its shard of the global
        batch (the DataLoader already gives disjoint slices).  Assembly is
        explicit per-device placement + ``make_array_from_single_device_
        arrays`` (SNIPPETS [2]): each addressable device receives exactly
        its rows of the logical global array, so a resumed run on a
        DIFFERENT topology (more hosts, a reshaped mesh) feeds the same
        global batch without any host gather.  When the addressable shards
        are not one contiguous block of rows (an exotic device order this
        framework's meshes don't produce), placement falls back to
        ``make_array_from_process_local_data``.
        """
        batch_size = 1
        for nm in self.batch_axes:
            batch_size *= self.mesh.shape[nm]

        def _shard(x):
            x = np.asarray(x)
            global_rows = x.shape[0] * jax.process_count()
            if global_rows % batch_size != 0:
                if jax.process_count() > 1:
                    # A replicated fallback would be *wrong* multi-host: each
                    # process holds different rows of what the runtime would
                    # treat as one identical replicated array.
                    raise ValueError(
                        f"global batch {global_rows} not divisible by mesh batch "
                        f"axes ({batch_size}); use drop_last=True or pad the "
                        "final batch"
                    )
                axes = None
            else:
                axes = self.batch_axes
            sharding = NamedSharding(self.mesh, P(axes, *([None] * (x.ndim - 1))))
            return self._assemble_global(x, sharding, global_rows)

        return jax.tree.map(_shard, batch)

    def _assemble_global(self, x, sharding, global_rows: int):
        """Explicit global-batch assembly: device_put each addressable
        device's row slice, then bind the buffers into one global array.
        The host's rows sit at one contiguous block of the global batch
        (this framework's meshes are row-major with processes owning
        contiguous device blocks); the block's offset is read off the
        sharding's own index map rather than assumed."""
        global_shape = (global_rows,) + x.shape[1:]
        idx_map = sharding.addressable_devices_indices_map(global_shape)

        def rows(idx):
            rsl = idx[0] if idx else slice(None)
            start = 0 if rsl.start is None else int(rsl.start)
            stop = global_shape[0] if rsl.stop is None else int(rsl.stop)
            return start, stop

        spans = {dev: rows(idx) for dev, idx in idx_map.items()}
        row0 = min(s for s, _ in spans.values())
        row1 = max(e for _, e in spans.values())
        if row1 - row0 != x.shape[0]:
            # addressable shards don't tile this host's block contiguously:
            # let jax work out the local-to-global correspondence
            return jax.make_array_from_process_local_data(sharding, x)
        buffers = [jax.device_put(x[s - row0:e - row0], dev)
                   for dev, (s, e) in spans.items()]
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, buffers)
