"""Pipeline parallelism — GPipe-style microbatched stage execution.

The reference has no pipeline parallelism (SURVEY.md §2.2: DP is its only
strategy); this module is scaling headroom the TPU mesh design reserves
alongside dp/fsdp/tp (mesh.py) and sp (ring.py / ulysses.py).

Design: the layer stack is cut into ``pp`` equal stages; each device on the
``pp`` mesh axis holds one stage's params (leading-axis sharded).  Inside a
``shard_map``, a `lax.scan` runs the classic GPipe schedule: at step ``t``
stage ``s`` computes microbatch ``t - s`` (bubbles at the edges), then
hands its activation to stage ``s+1`` via a neighbor `lax.ppermute` — the
point-to-point transfer rides one ICI hop, exactly like the k/v rotation
in ring attention.  Everything is differentiable (`scan` + `ppermute` have
transpose rules), so one `jax.grad` over the wrapped function trains the
whole pipeline; per-step `jax.checkpoint` keeps activation memory at
O(microbatches + steps·stage_depth) instead of O(steps·depth).

The stage function must be *uniform* across stages (same param pytree
structure), which holds for this framework's Transformer whenever
``depth % pp == 0`` and the attention-type cycle length divides the stage
depth — true for the CUB config (cycle 4, depth 8: each stage is one full
[full, axial_row, axial_col, conv_like] cycle).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map


def stack_stage_params(params: dict, depth: int, pp: int,
                       layer_prefixes: tuple = ("layers_{i}_attn",
                                                "layers_{i}_ff")) -> dict:
    """Restructure a Transformer param tree (flat ``layers_{i}_attn`` /
    ``layers_{i}_ff`` children) into a stage-stacked tree: the same names
    re-indexed per stage (``i`` in [0, depth/pp)), every leaf gaining a
    leading ``pp`` axis to shard over the pipeline mesh axis."""
    assert depth % pp == 0, f"depth {depth} not divisible by pp {pp}"
    per = depth // pp
    out: dict = {}
    for local in range(per):
        for prefix in layer_prefixes:
            name_local = prefix.format(i=local)
            stages = [params[prefix.format(i=stage * per + local)]
                      for stage in range(pp)]
            out[name_local] = jax.tree.map(
                lambda *leaves: jnp.stack(leaves), *stages)
    # non-layer params (none in Transformer today) would need replication;
    # be loud rather than silently dropping them.
    layer_names = {prefix.format(i=i) for prefix in layer_prefixes
                   for i in range(depth)}
    extra = set(params) - layer_names
    assert not extra, f"non-layer params not supported in pipeline: {extra}"
    return out


def unstack_stage_params(stacked: dict, depth: int, pp: int,
                         layer_prefixes: tuple = ("layers_{i}_attn",
                                                  "layers_{i}_ff")) -> dict:
    """Inverse of :func:`stack_stage_params`: stage-stacked leaves (leading
    ``pp`` axis) back to the flat ``layers_{i}_*`` tree — for writing
    standard checkpoints and running the (non-pipelined) sampler."""
    assert depth % pp == 0, f"depth {depth} not divisible by pp {pp}"
    per = depth // pp
    out: dict = {}
    for local in range(per):
        for prefix in layer_prefixes:
            stacked_leaf = stacked[prefix.format(i=local)]
            for stage in range(pp):
                out[prefix.format(i=stage * per + local)] = jax.tree.map(
                    lambda leaf, s=stage: leaf[s], stacked_leaf)
    return out


def pipeline_apply(stage_fn: Callable, stacked_params, x, *,
                   mesh: Mesh, pp_axis: str = "pp",
                   num_microbatches: int, remat: bool = True,
                   dp_axis: Optional[str] = None) -> jax.Array:
    """Run ``stage_fn`` as a ``pp``-stage GPipe pipeline over ``mesh``.

    stage_fn(stage_params, h) -> h, applied by every pipeline stage to its
    shard of ``stacked_params`` (leading axis ``pp``).  ``x`` is the global
    batch [b, n, d]; it is split into ``num_microbatches`` equal
    microbatches along axis 0.  Returns [b, n, d].
    """
    pp = mesh.shape[pp_axis]
    b = x.shape[0]
    m = num_microbatches
    assert b % m == 0, f"batch {b} not divisible by microbatches {m}"
    mb = b // m
    xs = x.reshape(m, mb, *x.shape[1:])

    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def run(params, xs):
        params = jax.tree.map(lambda p: p[0], params)  # my stage's slice
        idx = jax.lax.axis_index(pp_axis)
        steps = m + pp - 1
        state0 = jnp.zeros_like(xs[0])
        out0 = jnp.zeros_like(xs)

        def step(carry, t):
            state, outs = carry
            # stage 0 ingests microbatch t (clamped during drain bubbles);
            # later stages consume the neighbor's activation
            feed = xs[jnp.minimum(t, m - 1)]
            h_in = jnp.where(idx == 0, feed, state)
            h_out = body(params, h_in)
            # the last stage completed microbatch t-(pp-1) at this step
            done = t - (pp - 1)
            outs = jax.lax.cond(
                (idx == pp - 1) & (done >= 0),
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, h_out, jnp.maximum(done, 0), axis=0),
                lambda o: o, outs)
            state_next = jax.lax.ppermute(
                h_out, pp_axis, [(d, d + 1) for d in range(pp - 1)])
            return (state_next, outs), None

        (_, outs), _ = jax.lax.scan(step, (state0, out0), jnp.arange(steps))
        # only the last stage holds real outputs; broadcast them to every
        # stage so the out_spec can be pp-replicated
        outs = jax.lax.psum(
            jnp.where(idx == pp - 1, outs, jnp.zeros_like(outs)), pp_axis)
        return outs

    if dp_axis is not None:
        assert dp_axis in mesh.axis_names, (
            f"dp_axis {dp_axis!r} is not a mesh axis {mesh.axis_names}")
    # microbatch axis stays whole per stage; batch-within-microbatch on dp
    x_spec = P(None, dp_axis)
    fn = shard_map(
        run, mesh=mesh, in_specs=(P(pp_axis), x_spec), out_specs=x_spec,
        check_vma=False)
    outs = fn(stacked_params, xs)
    return outs.reshape(b, *x.shape[1:])


def pipeline_transformer(tf, params: dict, *, mesh: Mesh,
                         pp_axis: str = "pp", num_microbatches: int,
                         dp_axis: Optional[str] = None,
                         remat: bool = True):
    """Pipeline a framework Transformer: cut its depth into ``pp`` stages
    and run the GPipe schedule.  ``tf`` is the *full* Transformer module,
    ``params`` its params; returns (stage module, stacked params, apply fn)
    so callers can reuse the stacking across steps.

    Requires ``depth % pp == 0`` and the attn-type cycle to divide the
    stage depth (so every stage is structurally identical).  Executors
    whose semantics span the whole depth (reversible two-stream), per-layer
    sparse layout seeds, in-attention sequence parallelism, and dropout are
    rejected rather than silently diverging from ``tf.apply``.
    """
    pp = mesh.shape[pp_axis]
    assert tf.depth % pp == 0, f"depth {tf.depth} not divisible by pp={pp}"
    per = tf.depth // pp
    cycle = len(tf.attn_types) if tf.attn_types else 1
    assert per % cycle == 0, (
        f"stage depth {per} must be a multiple of the attn-type cycle "
        f"{cycle} so all stages share one structure")
    attn_types = tf.attn_types or ("full",)
    assert "sparse" not in attn_types, (
        "pipeline stages re-derive sparse layouts from stage-local layer "
        "indices, diverging from the full model's per-layer seeds; "
        "pipelining the 'sparse' variant is not supported")
    assert not tf.reversible, (
        "the reversible two-stream executor spans the whole depth and "
        "cannot be cut into independent stages")
    assert tf.ring_axis is None, (
        "combining in-attention sequence parallelism with pipelining is "
        "not supported")
    assert tf.attn_dropout == 0 and tf.ff_dropout == 0, (
        "pipeline stages run deterministically; dropout would be silently "
        "disabled")
    assert tf.ff_experts <= 1, (
        "pipeline stages apply without mutable collections, so the MoE "
        "load-balance aux losses would silently vanish")

    # clone so every other field (dtype, use_pallas, remat, ...) carries over
    stage = tf.clone(depth=per, name=None)
    stacked = stack_stage_params(params, tf.depth, pp)

    def stage_fn(stage_params, h):
        return stage.apply({"params": stage_params}, h)

    def apply_fn(stacked_params, x):
        return pipeline_apply(
            stage_fn, stacked_params, x, mesh=mesh, pp_axis=pp_axis,
            num_microbatches=num_microbatches, dp_axis=dp_axis, remat=remat)

    return stage, stacked, apply_fn
