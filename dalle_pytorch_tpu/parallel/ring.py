"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has no long-context machinery (SURVEY.md §5.7: its levers are
sparse attention and reversible depth at a fixed seq_len ≈ 1104).  A
TPU-native framework treats long context as a first-class scaling axis:
shard the *sequence* over an ``sp`` mesh axis and compute exact attention by
rotating key/value shards around the ICI ring (`lax.ppermute`) while
accumulating the softmax online — per-device memory O(n/sp · n/sp) instead
of O(n²), full overlap of compute with neighbor transfers, and exact (not
approximate) results.

Two entry points:
* ``ring_attention(q, k, v, axis_name=...)`` — call inside ``shard_map``
  with q/k/v already sequence-sharded ([b, h, n_local, dh] per device).
* ``ring_attention_sharded(q, k, v, mesh, ...)`` — standalone: wraps the
  shard_map over ``mesh`` with the batch on 'dp' and sequence on 'sp'.

Masking reuses the same `AttnPattern` predicate as every other attention in
the framework (ops/attention.py), evaluated at *global* positions, so the
DALLE variants (full / axial / conv_like / sparse) all work sequence-
parallel.  Differentiable by construction (ppermute's transpose is the
inverse ppermute; the scan is unrolled by XLA's autodiff).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import AttnPattern, _allowed
from .mesh import shard_map

NEG_INF = -1e30


def _chunk_mask(pattern: Optional[AttnPattern], causal: bool,
                q_off, k_off, n_q: int, n_k: int, layout=None):
    """Boolean [n_q, n_k] mask for a (query-chunk, key-chunk) pair whose
    global offsets are (traced) ``q_off`` / ``k_off``."""
    i = q_off + jax.lax.broadcasted_iota(jnp.int32, (n_q, n_k), 0)
    j = k_off + jax.lax.broadcasted_iota(jnp.int32, (n_q, n_k), 1)
    if pattern is None:
        return (j <= i) if causal else jnp.ones((n_q, n_k), bool)
    return _allowed(pattern, i, j, jnp, layout=layout)


def ring_attention(q, k, v, *, axis_name: str,
                   pattern: Optional[AttnPattern] = None,
                   causal: bool = True) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis_name``.

    q/k/v: local shards [b, h, n_local, dh]; every device holds a distinct
    contiguous chunk of the global sequence, ordered by its axis index.
    Returns the local output shard [b, h, n_local, dh].
    """
    sp = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, nl, dh = q.shape
    scale = dh ** -0.5
    layout = None
    if pattern is not None and pattern.variant == "sparse":
        layout = jnp.asarray(pattern.block_layout())

    qf = q.astype(jnp.float32) * scale
    m0 = jnp.full((b, h, nl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, nl, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, nl, dh), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def accumulate(r, k_r, v_r, m, l, acc):
        """Online-softmax update against the chunk currently held, which
        originated on device (idx - r) mod sp."""
        src = jax.lax.rem(idx - r + sp, sp)
        s = jnp.einsum("bhid,bhjd->bhij", qf, k_r.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        allow = _chunk_mask(pattern, causal, idx * nl, src * nl, nl, nl,
                            layout=layout)
        s = jnp.where(allow[None, None], s, NEG_INF)

        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)  # fully-masked rows -> 0
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhij,bhjd->bhid", p, v_r.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    def step(r, carry):
        k_r, v_r, m, l, acc = carry
        m, l, acc = accumulate(r, k_r, v_r, m, l, acc)
        # rotate k/v to the next device; overlaps with the next step's
        # compute under XLA's async collective scheduling
        k_nxt = jax.lax.ppermute(k_r, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_r, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    # sp-1 rotations; the final chunk is consumed without a (dead) rotation
    k_r, v_r, m, l, acc = jax.lax.fori_loop(0, sp - 1, step,
                                            (k, v, m0, l0, acc0))
    m, l, acc = accumulate(sp - 1, k_r, v_r, m, l, acc)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, *, sp_axis: str = "sp",
                           dp_axis: Optional[str] = "dp",
                           pattern: Optional[AttnPattern] = None,
                           causal: bool = True) -> jax.Array:
    """Standalone wrapper: q/k/v are global [b, h, n, dh]; the sequence dim
    is sharded over `sp_axis` (and batch over `dp_axis` if present)."""
    dp = dp_axis if dp_axis and dp_axis in mesh.axis_names else None
    spec = P(dp, None, sp_axis, None)

    fn = partial(ring_attention, axis_name=sp_axis, pattern=pattern,
                 causal=causal)
    sharded = shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return sharded(q, k, v)
