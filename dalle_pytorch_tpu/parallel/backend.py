"""Distributed backends — registry + abstract API + Single/GSPMD backends.

API parity with the reference's pluggable backend layer
(`/root/reference/dalle_pytorch/distributed_utils.py:22-89`,
`distributed_backends/distributed_backend.py:12-178`): the same conceptual
surface — ``initialize / get_world_size / get_rank / get_local_rank /
is_root_worker / is_local_root_worker / local_barrier / distribute /
average_all / check_batch_size`` — but TPU-native underneath:

* ``SingleBackend`` = the reference's DummyBackend (one process, n devices —
  data parallelism still happens via the mesh, there's just one host).
* ``GSPMDBackend`` = DeepSpeed/Horovod replacement.  ``initialize`` calls
  ``jax.distributed.initialize`` (the NCCL/MPI-rendezvous analog);
  ``distribute`` hands back a `Partitioner` (mesh + shardings) instead of
  wrapping the model — grad allreduce becomes a `psum` XLA emits over ICI;
  ``average_all`` is a cross-process mean for host-side metrics.

"world size" counts JAX *processes* (hosts), matching the reference's rank
semantics; device-level parallelism is the mesh's job.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from .mesh import Partitioner, make_mesh


class DistributedBackend:
    """Abstract backend (contract of ref distributed_backend.py:12-178)."""

    BACKEND_NAME = "None"

    ROOT_RANK = 0

    def __init__(self):
        self._initialized = False

    def has_backend(self) -> bool:
        return True

    def wrap_arg_parser(self, parser):
        return parser

    def initialize(self):
        self._initialize()
        self._initialized = True
        return self

    def _initialize(self):
        raise NotImplementedError

    def _require_init(self):
        assert self._initialized, (
            f"backend {self.BACKEND_NAME} not initialized; call initialize()"
        )

    def get_world_size(self) -> int:
        self._require_init()
        return self._get_world_size()

    def get_rank(self) -> int:
        self._require_init()
        return self._get_rank()

    def get_local_rank(self) -> int:
        self._require_init()
        return self._get_local_rank()

    def is_root_worker(self) -> bool:
        return self.get_rank() == self.ROOT_RANK

    def is_local_root_worker(self) -> bool:
        return self.get_local_rank() == self.ROOT_RANK

    def in_distributed_mode(self) -> bool:
        return self.get_world_size() > 1

    def local_barrier(self):
        raise NotImplementedError

    def distribute(self, **kwargs) -> Partitioner:
        """Return the Partitioner that owns mesh + shardings.

        Where the reference's `distribute()` wraps (model, optimizer, data,
        scheduler) into engine objects (deepspeed_backend.py:63-95), under
        GSPMD nothing needs wrapping: the caller jits its train step with the
        Partitioner's shardings and XLA inserts the collectives.
        """
        raise NotImplementedError

    def average_all(self, value):
        """Average a host-side metric across processes
        (ref `_average_all`: NCCL all_reduce/world, deepspeed_backend.py:97-103)."""
        raise NotImplementedError

    def check_batch_size(self, batch_size: int):
        assert batch_size >= self.get_world_size(), (
            f"batch size {batch_size} smaller than world size {self.get_world_size()}"
        )


class SingleBackend(DistributedBackend):
    """Single-process backend (ref DummyBackend, dummy_backend.py). All the
    local devices still form a mesh — 'dummy' means one host, not one chip."""

    BACKEND_NAME = "Single"

    def __init__(self, mesh=None, mesh_fsdp: int = 1, mesh_tp: int = 1,
                 mesh_dcn_dp: int = 1):
        super().__init__()
        self._mesh = mesh
        self.mesh_fsdp = mesh_fsdp
        self.mesh_tp = mesh_tp
        self.mesh_dcn_dp = mesh_dcn_dp

    def _initialize(self):
        pass

    def _get_world_size(self) -> int:
        return 1

    def _get_rank(self) -> int:
        return 0

    def _get_local_rank(self) -> int:
        return 0

    def local_barrier(self):
        pass

    def distribute(self, mesh=None, plan=None, **kwargs) -> Partitioner:
        # a single process can still drive several local chips: a declarative
        # ParallelPlan wins (it IS the mesh-shape contract), else honor the
        # legacy mesh-shape flags (dp absorbs the rest)
        if mesh is None and plan is not None:
            return Partitioner(plan=plan, **kwargs)
        mesh = mesh or self._mesh or make_mesh(
            fsdp=self.mesh_fsdp, tp=self.mesh_tp, dcn_dp=self.mesh_dcn_dp)
        return Partitioner(mesh=mesh, plan=plan, **kwargs)

    def average_all(self, value):
        return value


# Env vars consulted by _cluster_env_hints (exported so tests can clear
# exactly this set when simulating a hint-free host).
CLUSTER_HINT_VARS = ("MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
                     "SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE")


def _cluster_env_hints() -> list:
    """Environment markers that this process was launched as part of a
    *multi-host* job (TPU pod / MegaScale / SLURM / OpenMPI).  When any is
    present, a failed ``jax.distributed.initialize`` must be fatal: silently
    degrading to world_size=1 would train N independent model copies — the
    worst kind of quiet corruption on a real pod.

    Every check is count-based, not presence-based: single-host TPU VMs set
    e.g. a one-entry ``TPU_WORKER_HOSTNAMES`` too, and there the soft
    single-process fallback is the correct behavior."""
    import os

    hints = []
    # graftlint: disable=ENV001 (address-valued: any non-empty value IS the hint)
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        hints.append("MEGASCALE_COORDINATOR_ADDRESS")  # multislice-only var
    workers = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
               if h.strip()]
    if len(workers) > 1:
        hints.append("TPU_WORKER_HOSTNAMES")
    for var in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(os.environ.get(var, "0")) > 1:
                hints.append(var)
        except ValueError:
            pass
    return hints


class GSPMDBackend(DistributedBackend):
    """Multi-host backend over the JAX distributed runtime + GSPMD."""

    BACKEND_NAME = "GSPMD"

    def __init__(self, coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 mesh=None, mesh_fsdp: int = 1, mesh_tp: int = 1,
                 mesh_dcn_dp: int = 1):
        super().__init__()
        self.coordinator_address = coordinator_address
        self.num_processes = num_processes
        self.process_id = process_id
        self._mesh = mesh
        self.mesh_fsdp = mesh_fsdp
        self.mesh_tp = mesh_tp
        self.mesh_dcn_dp = mesh_dcn_dp

    def wrap_arg_parser(self, parser):
        parser.add_argument("--coordinator_address", type=str, default=None,
                            help="host:port of JAX process 0")
        parser.add_argument("--num_processes", type=int, default=None)
        parser.add_argument("--process_id", type=int, default=None)
        return parser

    def _initialize(self):
        # jax.distributed.initialize is the rendezvous analog of
        # deepspeed.init_distributed (ref deepspeed_backend.py:35-36); with no
        # args it picks up TPU pod metadata / cluster env vars.  Must run
        # before any other JAX call initializes the runtime.
        kwargs = {}
        explicit = self.coordinator_address is not None or self.num_processes is not None
        if explicit:
            kwargs = dict(coordinator_address=self.coordinator_address,
                          num_processes=self.num_processes,
                          process_id=self.process_id)
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as e:
            if explicit:
                raise
            hints = _cluster_env_hints()
            if hints:
                # The environment says this is one process of a pod job; a
                # soft fallback here would train N independent model copies.
                raise RuntimeError(
                    "GSPMDBackend: jax.distributed.initialize failed "
                    f"({e!r}) but cluster environment hints are present "
                    f"({', '.join(hints)}) — refusing to fall back to "
                    "single-process. Pass --coordinator_address/"
                    "--num_processes/--process_id explicitly or fix the "
                    "cluster rendezvous."
                ) from e
            # Truly no cluster environment — running single-process.  Still
            # warn: if the user expected a pod, they should know.
            import warnings

            warnings.warn(
                f"GSPMDBackend: jax.distributed.initialize failed ({e!r}); "
                "continuing single-process. If this is a multi-host run, pass "
                "--coordinator_address/--num_processes/--process_id explicitly.",
                RuntimeWarning,
            )

    def _get_world_size(self) -> int:
        return jax.process_count()

    def _get_rank(self) -> int:
        return jax.process_index()

    def _get_local_rank(self) -> int:
        # processes are 1:1 with hosts; local rank of the lead process is 0
        return 0

    def local_barrier(self):
        # The reference barriers around rank-coordinated downloads
        # (vae.py:67-93).  A tiny replicated psum is a full sync point.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("dalle_pytorch_tpu_barrier")

    def distribute(self, mesh=None, plan=None, **kwargs) -> Partitioner:
        if mesh is None and plan is not None:
            return Partitioner(plan=plan, **kwargs)
        mesh = mesh or self._mesh or make_mesh(
            fsdp=self.mesh_fsdp, tp=self.mesh_tp, dcn_dp=self.mesh_dcn_dp)
        return Partitioner(mesh=mesh, plan=plan, **kwargs)

    def average_all(self, value):
        if jax.process_count() == 1:
            return value
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.asarray(value))
        return gathered.mean(axis=0)


# --- registry (ref distributed_utils.py:22-89) ---

BACKENDS = [SingleBackend, GSPMDBackend]

is_distributed: Optional[bool] = None
backend: Optional[DistributedBackend] = None


def wrap_arg_parser(parser):
    parser.add_argument(
        "--distributed_backend", "--distr_backend", type=str, default=None,
        help="which distributed backend to use (Single, GSPMD)",
    )
    # mesh shape is backend-independent (a single process can drive several
    # local chips); dp absorbs the devices the other axes don't claim
    parser.add_argument("--plan", type=str, default=None,
                        help="declarative parallelism plan (parallel/"
                             "plan.py): a registry name (dp, fsdp, tp, "
                             "sp-ring, sp-ulysses, pp) or an axis spec like "
                             "'dp2.tp4', 'fsdp4', 'sp-ring2', 'pp2'.  Wins "
                             "over the individual --mesh_*/--pipeline_"
                             "stages flags; recorded (with the topology) in "
                             "every checkpoint manifest, so a preempted run "
                             "relaunched with a DIFFERENT --plan reshards "
                             "its restore onto the new mesh (elastic "
                             "resume)")
    parser.add_argument("--mesh_fsdp", type=int, default=1,
                        help="fsdp (ZeRO-style param/optimizer sharding) "
                             "ways of the device mesh")
    parser.add_argument("--mesh_tp", type=int, default=1,
                        help="tensor-parallel ways of the device mesh")
    parser.add_argument("--mesh_dcn_dp", type=int, default=1,
                        help="multi-slice: number of TPU slices joined over "
                             "DCN, laid out as outer data-parallel groups")
    for b in BACKENDS:
        parser = b().wrap_arg_parser(parser)
    return parser


def set_backend_from_args(args) -> DistributedBackend:
    """Select + construct the backend from CLI args (ref :48-69)."""
    global is_distributed, backend
    name = (getattr(args, "distributed_backend", None) or "Single").lower()
    for b_class in BACKENDS:
        if b_class.BACKEND_NAME.lower() == name:
            if b_class is GSPMDBackend:
                backend = GSPMDBackend(
                    coordinator_address=getattr(args, "coordinator_address", None),
                    num_processes=getattr(args, "num_processes", None),
                    process_id=getattr(args, "process_id", None),
                    mesh_fsdp=getattr(args, "mesh_fsdp", 1),
                    mesh_tp=getattr(args, "mesh_tp", 1),
                    mesh_dcn_dp=getattr(args, "mesh_dcn_dp", 1),
                )
            else:
                backend = b_class(
                    mesh_fsdp=getattr(args, "mesh_fsdp", 1),
                    mesh_tp=getattr(args, "mesh_tp", 1),
                    mesh_dcn_dp=getattr(args, "mesh_dcn_dp", 1),
                )
            is_distributed = b_class is not SingleBackend
            return backend
    raise ValueError(f"unknown backend {name}; choose from "
                     f"{[b.BACKEND_NAME for b in BACKENDS]}")


def using_backend(test_backend) -> bool:
    """Is the selected backend an instance of `test_backend` (ref :72-89)?"""
    assert backend is not None, "backend not selected yet"
    if isinstance(test_backend, str):
        return backend.BACKEND_NAME.lower() == test_backend.lower()
    return isinstance(backend, test_backend)
