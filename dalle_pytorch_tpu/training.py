"""Jit-compiled train steps for DiscreteVAE and DALLE.

The reference's training loop shape (forward -> backward -> allreduce ->
step, `train_vae.py:165-236`, `train_dalle.py:357-416`) collapses on TPU
into a single jitted function per model: loss + grads + optimizer update in
one XLA program, with gradient all-reduce inserted by GSPMD from the input
shardings.  Optimizer is optax Adam wrapped in ``inject_hyperparams`` so the
host-side schedules (utils/schedule.py) can set the lr between steps without
retracing — replacing torch's stateful ``ExponentialLR`` /
``ReduceLROnPlateau`` and the DeepSpeed engine's fused step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def _adam_chain(learning_rate, grad_clip_norm=0.0):
    steps = []
    if grad_clip_norm and float(grad_clip_norm) > 0:
        steps.append(optax.clip_by_global_norm(float(grad_clip_norm)))
    steps.append(optax.adam(learning_rate=learning_rate))
    return optax.chain(*steps)


def make_optimizer(learning_rate: float, grad_clip_norm: float = 0.0):
    """Adam, matching the reference's torch.optim.Adam defaults
    (train_dalle.py:284, train_vae.py:123), with optional global-norm clip
    (train_dalle.py:371-372).  The lr is an injected hyperparam so host-side
    schedules can change it without retracing."""
    return optax.inject_hyperparams(_adam_chain, static_args=("grad_clip_norm",))(
        learning_rate=learning_rate, grad_clip_norm=grad_clip_norm)


def set_learning_rate(opt_state, lr: float):
    """Host-side lr override for the next steps (plateau/exp schedules)."""
    opt_state.hyperparams["learning_rate"] = jnp.asarray(lr, dtype=jnp.float32)
    return opt_state


def make_vae_train_step(vae, tx, donate: bool = True):
    """(params, opt_state, images, rng, temp) -> (params, opt_state, loss, recons).

    `temp` is a traced scalar so the gumbel temperature anneal
    (train_vae.py:211-217) never retraces.
    """

    def train_step(params, opt_state, images, rng, temp):
        def loss_fn(p):
            loss, recons = vae.apply(
                {"params": p}, images, rng=rng, return_loss=True,
                return_recons=True, temp=temp)
            return loss, recons

        (loss, recons), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, recons

    return jax.jit(train_step, donate_argnums=(0, 1) if donate else ())


def make_dalle_train_step(dalle, tx, vae=None, donate: bool = True,
                          jit: bool = True):
    """DALLE step.  If `vae` is given, batches carry raw images and the
    (frozen) VAE encodes them to codes inside the step, mirroring the
    reference's in-forward `vae.get_codebook_indices` under no_grad
    (dalle_pytorch.py:459, :144-149); otherwise batches carry codes.

    ``jit=False`` returns the raw function (for embedding in a larger jitted
    program, e.g. a scan-of-steps benchmark loop).
    """

    def train_step(params, opt_state, vae_params, text, images_or_codes, rng):
        if vae is not None:
            codes = vae.apply({"params": vae_params}, images_or_codes,
                              method=type(vae).get_codebook_indices)
            codes = jax.lax.stop_gradient(codes)
        else:
            codes = images_or_codes

        def loss_fn(p):
            return dalle.apply({"params": p}, text, codes, return_loss=True,
                               deterministic=False,
                               rngs={"dropout": rng})

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if not jit:
        return train_step
    return jax.jit(train_step, donate_argnums=(0, 1) if donate else ())


def make_clip_train_step(clip, tx, donate: bool = True):
    def train_step(params, opt_state, text, images, text_mask):
        def loss_fn(p):
            return clip.apply({"params": p}, text, images, text_mask=text_mask,
                              return_loss=True)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1) if donate else ())
