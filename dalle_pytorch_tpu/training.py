"""Jit-compiled train steps for DiscreteVAE and DALLE.

The reference's training loop shape (forward -> backward -> allreduce ->
step, `train_vae.py:165-236`, `train_dalle.py:357-416`) collapses on TPU
into a single jitted function per model: loss + grads + optimizer update in
one XLA program, with gradient all-reduce inserted by GSPMD from the input
shardings.  Optimizer is optax Adam wrapped in ``inject_hyperparams`` so the
host-side schedules (utils/schedule.py) can set the lr between steps without
retracing — replacing torch's stateful ``ExponentialLR`` /
``ReduceLROnPlateau`` and the DeepSpeed engine's fused step.

Training health (utils/guardrails.py): every factory takes ``health=True``
to additionally return an on-device health vector — loss, global grad
norm, finite flag, computed *inside* the jitted step (no host syncs in
traced code) — and, with ``guard=True``, to suppress the optimizer update
by ``jnp.where`` masking when the gradients are non-finite, so one
pathological batch can never poison params/opt_state.  Health-enabled
steps take one extra traced scalar, ``fault_scale``, multiplying the loss
before differentiation: 1.0 in production, NaN / a spike factor under the
``grad_nan``/``loss_spike`` GRAFT_FAULTS sites (guardrails.fault_scale_for)
so the chaos suites poison the *real* gradients without retracing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from .obs import prof
from .parallel.mesh import shard_map
from .utils import guardrails


def _adam_chain(learning_rate, grad_clip_norm=0.0):
    steps = []
    if grad_clip_norm and float(grad_clip_norm) > 0:
        steps.append(optax.clip_by_global_norm(float(grad_clip_norm)))
    steps.append(optax.adam(learning_rate=learning_rate))
    return optax.chain(*steps)


def make_optimizer(learning_rate: float, grad_clip_norm: float = 0.0):
    """Adam, matching the reference's torch.optim.Adam defaults
    (train_dalle.py:284, train_vae.py:123), with optional global-norm clip
    (train_dalle.py:371-372).  The lr is an injected hyperparam so host-side
    schedules can change it without retracing."""
    return optax.inject_hyperparams(_adam_chain, static_args=("grad_clip_norm",))(
        learning_rate=learning_rate, grad_clip_norm=grad_clip_norm)


def set_learning_rate(opt_state, lr: float):
    """Host-side lr override for the next steps (plateau/exp schedules)."""
    opt_state.hyperparams["learning_rate"] = jnp.asarray(lr, dtype=jnp.float32)
    return opt_state


def _pin_update_shardings(partitioner, params, opt_state):
    """Constrain the updated params/opt_state to the Partitioner's input
    sharding rules.  Without this, GSPMD output-sharding propagation is
    free to place some updated leaves differently from their inputs — and
    jax silently DROPS buffer donation for exactly those leaves (graftspmd
    S2 caught ~2/3 of the donated leaves losing their aliases under the tp
    plan), so those params/opt_state buffers live twice across the
    update.

    The pin derives from the SAME Partitioner (itself built from the run's
    declarative ParallelPlan, parallel/plan.py) that sharded the inputs at
    init and restore — this function holds no sharding table of its own,
    so the three former hand-kept copies of the contract cannot drift."""
    if partitioner is None:
        return params, opt_state
    params = jax.lax.with_sharding_constraint(
        params, partitioner.param_shardings(params))
    opt_state = jax.lax.with_sharding_constraint(
        opt_state, partitioner.param_shardings(opt_state))
    return params, opt_state


def make_vae_train_step(vae, tx, donate: bool = True, health: bool = False,
                        guard: bool = True, partitioner=None):
    """(params, opt_state, images, rng, temp) -> (params, opt_state, loss, recons).

    `temp` is a traced scalar so the gumbel temperature anneal
    (train_vae.py:211-217) never retraces.  With ``health=True`` the step
    takes a trailing ``fault_scale`` scalar and additionally returns the
    on-device health vector (module docstring).  ``partitioner`` (the
    run's mesh Partitioner) pins the updated params/opt_state to the
    input sharding rules so donation survives GSPMD propagation.
    """

    def train_step(params, opt_state, images, rng, temp, *fault_scale):
        def loss_fn(p):
            loss, recons = vae.apply(
                {"params": p}, images, rng=rng, return_loss=True,
                return_recons=True, temp=temp)
            if health:
                loss = loss * fault_scale[0]
            return loss, recons

        (loss, recons), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        if health:
            with prof.scope("optimizer"):
                params, opt_state, hv = guardrails.guarded_update(
                    tx, grads, opt_state, params, loss=loss, guard=guard)
                params, opt_state = _pin_update_shardings(partitioner, params,
                                                          opt_state)
            return params, opt_state, loss, recons, hv
        with prof.scope("optimizer"):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params, opt_state = _pin_update_shardings(partitioner, params,
                                                      opt_state)
        return params, opt_state, loss, recons

    return jax.jit(train_step, donate_argnums=(0, 1) if donate else ())


def _dalle_loss(dalle, params, text, codes, rng):
    """Training loss incl. the MoE load-balance aux when the model routes
    its FFs through experts (the sown 'losses' collection would silently
    vanish without mutable=['losses'])."""
    if dalle.cfg.ff_experts > 1:
        loss, state = dalle.apply(
            {"params": params}, text, codes, return_loss=True,
            deterministic=False, rngs={"dropout": rng}, mutable=["losses"])
        aux = sum(jax.tree.leaves(state["losses"]))
        return loss + dalle.cfg.ff_aux_weight * aux
    return dalle.apply({"params": params}, text, codes, return_loss=True,
                       deterministic=False, rngs={"dropout": rng})


def make_dalle_train_step(dalle, tx, vae=None, donate: bool = True,
                          jit: bool = True, health: bool = False,
                          guard: bool = True, partitioner=None):
    """DALLE step.  If `vae` is given, batches carry raw images and the
    (frozen) VAE encodes them to codes inside the step, mirroring the
    reference's in-forward `vae.get_codebook_indices` under no_grad
    (dalle_pytorch.py:459, :144-149); otherwise batches carry codes.

    ``jit=False`` returns the raw function (for embedding in a larger jitted
    program, e.g. a scan-of-steps benchmark loop).  With ``health=True``
    the step takes a trailing ``fault_scale`` scalar and additionally
    returns the on-device health vector (module docstring).
    ``partitioner`` (the run's mesh Partitioner) pins the updated
    params/opt_state to the input sharding rules so donation survives
    GSPMD propagation.
    """

    def train_step(params, opt_state, vae_params, text, images_or_codes,
                   rng, *fault_scale):
        if vae is not None:
            codes = vae.apply({"params": vae_params}, images_or_codes,
                              method=type(vae).get_codebook_indices)
            codes = jax.lax.stop_gradient(codes)
        else:
            codes = images_or_codes

        def loss_fn(p):
            loss = _dalle_loss(dalle, p, text, codes, rng)
            return loss * fault_scale[0] if health else loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if health:
            with prof.scope("optimizer"):
                params, opt_state, hv = guardrails.guarded_update(
                    tx, grads, opt_state, params, loss=loss, guard=guard)
                params, opt_state = _pin_update_shardings(partitioner, params,
                                                          opt_state)
            return params, opt_state, loss, hv
        with prof.scope("optimizer"):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params, opt_state = _pin_update_shardings(partitioner, params,
                                                      opt_state)
        return params, opt_state, loss

    if not jit:
        return train_step
    return jax.jit(train_step, donate_argnums=(0, 1) if donate else ())


def make_dalle_sp_train_step(dalle, tx, mesh, dp_axis: str = "dp",
                             donate: bool = True, health: bool = False,
                             guard: bool = True):
    """Sequence-parallel DALLE step: the loss runs inside a ``shard_map``
    over (dp, sp) — batch sharded over ``dp_axis``, the sequence over
    ``cfg.ring_axis`` with ring/Ulysses collectives making attention exact
    (parallel/ring.py, parallel/ulysses.py), params replicated.  Output-
    equivalent to the dense step (DALLE._sp_loss psums the per-shard phase
    CE against global positions); the backward differentiates straight
    through the shard_map (ppermute/all-to-all have transpose rules).

    The reference's only strategy is DP (SURVEY.md §2.2); this is how the
    framework trains sequences a single chip's HBM can't hold.
    """
    from jax.sharding import PartitionSpec as P

    cfg = dalle.cfg
    axis = cfg.ring_axis
    assert axis is not None and cfg.sp_size > 1, (
        "sequence-parallel step needs cfg.ring_axis + cfg.sp_size > 1 "
        "(set DALLEConfig(ring_axis='sp', sp_size=N))")
    assert axis in mesh.axis_names and mesh.shape[axis] == cfg.sp_size, (
        f"mesh axis {axis!r} of size {cfg.sp_size} required, "
        f"got mesh {dict(mesh.shape)}")
    assert cfg.ff_experts <= 1, (
        "combining MoE with sequence parallelism is not supported")

    def global_loss(params, text, codes, rng):
        def local(params, text, codes, rng):
            # decorrelate dropout across sequence shards (same key + same
            # local shape would otherwise draw identical masks per shard)
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
            loss = dalle.apply({"params": params}, text, codes,
                               return_loss=True, deterministic=False,
                               rngs={"dropout": rng})
            if health:
                # the skip decision must be COLLECTIVE: the per-shard
                # losses are genuinely different values, so the finite
                # flags are pmin-combined over the whole (dp, sp) mesh —
                # every shard sees the same verdict or they would diverge
                # (the average_and_poll pattern, on device)
                ok = guardrails.collective_all_finite(loss, (dp_axis, axis))
                return jax.lax.pmean(loss, dp_axis), ok
            return jax.lax.pmean(loss, dp_axis)

        out_specs = (P(), P()) if health else P()  # graftlint: disable=PLAN001 (shard_map arg placement for the sp step — batch over dp, params replicated; not a param-tree sharding, so the rule table does not apply)
        return shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(dp_axis), P(dp_axis), P()),  # graftlint: disable=PLAN001 (same: per-arg shard_map specs, not PARTITION_RULES territory)
            out_specs=out_specs, check_vma=False)(params, text, codes, rng)

    def train_step(params, opt_state, _vae_params, text, codes, rng,
                   *fault_scale):
        if health:
            def loss_fn(p):
                loss, ok = global_loss(p, text, codes, rng)
                return loss * fault_scale[0], ok

            (loss, ok), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            with prof.scope("optimizer"):
                params, opt_state, hv = guardrails.guarded_update(
                    tx, grads, opt_state, params, loss=loss, extra_ok=ok,
                    guard=guard)
            return params, opt_state, loss, hv
        loss, grads = jax.value_and_grad(global_loss)(params, text, codes, rng)
        with prof.scope("optimizer"):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1) if donate else ())


def make_dalle_pp_train_step(dalle, tx, params, mesh, *,
                             num_microbatches: int, pp_axis: str = "pp",
                             dp_axis: str = "dp", donate: bool = True,
                             health: bool = False, guard: bool = True):
    """Pipeline-parallel DALLE step (GPipe schedule, parallel/pipeline.py).

    The transformer stack — where the params and FLOPs are — is cut into
    ``mesh.shape[pp_axis]`` stages; embeddings and the logits head run
    replicated outside the pipeline (they are a few percent of the work).
    Returns ``(train_step, pp_params)`` where ``pp_params`` is the
    restructured tree ``{'outer': <non-transformer params>, 'stages':
    <stage-stacked transformer params>}`` the step trains on; convert back
    with :func:`pp_params_to_dense` for checkpoints/sampling.
    """
    from .models.dalle import DALLE, transformer_kwargs
    from .ops.transformer import Transformer
    from .parallel.pipeline import pipeline_transformer

    cfg = dalle.cfg
    tf = Transformer(**transformer_kwargs(cfg))
    _, stacked, apply_fn = pipeline_transformer(
        tf, params["transformer"], mesh=mesh, pp_axis=pp_axis,
        num_microbatches=num_microbatches, dp_axis=dp_axis)
    pp_params = {"outer": {k: v for k, v in params.items()
                           if k != "transformer"},
                 "stages": stacked}

    def loss_fn(p, text, codes):
        tokens = dalle.apply({"params": p["outer"]}, text, codes,
                             cfg.onehot_embed, method=DALLE.embed_sequence)
        # "pipeline" charges the schedule machinery (microbatch buffers,
        # ppermute shifts); the blocks' own scopes win inside (innermost
        # graftprof frame takes the eqn)
        with prof.scope("pipeline"):
            h = apply_fn(p["stages"], tokens)
        return dalle.apply({"params": p["outer"]}, h, text, codes,
                           method=DALLE.loss_from_hidden)

    def train_step(pp_params, opt_state, _vae_params, text, codes, _rng,
                   *fault_scale):
        def scaled(p, text, codes):
            loss = loss_fn(p, text, codes)
            return loss * fault_scale[0] if health else loss

        loss, grads = jax.value_and_grad(scaled)(pp_params, text, codes)
        if health:
            # grads/loss here are jit-level global values (GSPMD reduces
            # them identically on every host and stage), so the plain
            # sentinel is already a collective decision
            with prof.scope("optimizer"):
                pp_params, opt_state, hv = guardrails.guarded_update(
                    tx, grads, opt_state, pp_params, loss=loss, guard=guard)
            return pp_params, opt_state, loss, hv
        with prof.scope("optimizer"):
            updates, opt_state = tx.update(grads, opt_state, pp_params)
            pp_params = optax.apply_updates(pp_params, updates)
        return pp_params, opt_state, loss

    return (jax.jit(train_step, donate_argnums=(0, 1) if donate else ()),
            pp_params)


def pp_params_to_dense(dalle, pp_params, mesh, pp_axis: str = "pp"):
    """Invert the pipeline restructuring: ``{'outer', 'stages'}`` back to
    the standard DALLE param tree (for checkpoints and the sampler)."""
    from .parallel.pipeline import unstack_stage_params

    dense = dict(pp_params["outer"])
    dense["transformer"] = unstack_stage_params(
        pp_params["stages"], dalle.cfg.depth, mesh.shape[pp_axis])
    return dense


def make_clip_train_step(clip, tx, donate: bool = True, health: bool = False,
                         guard: bool = True, partitioner=None):
    """CLIP contrastive step (text/image towers, symmetric CE).
    ``partitioner`` pins the updated params/opt_state to the input
    sharding rules so donation survives GSPMD propagation."""
    def train_step(params, opt_state, text, images, text_mask, *fault_scale):
        def loss_fn(p):
            loss = clip.apply({"params": p}, text, images,
                              text_mask=text_mask, return_loss=True)
            return loss * fault_scale[0] if health else loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if health:
            with prof.scope("optimizer"):
                params, opt_state, hv = guardrails.guarded_update(
                    tx, grads, opt_state, params, loss=loss, guard=guard)
                params, opt_state = _pin_update_shardings(partitioner, params,
                                                          opt_state)
            return params, opt_state, loss, hv
        with prof.scope("optimizer"):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params, opt_state = _pin_update_shardings(partitioner, params,
                                                      opt_state)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1) if donate else ())


# Every train-step factory in this module, by name.  tools/spmd_check.py
# (the graftspmd analyzer) traces each entry under every applicable
# parallelism plan — collective order, donation audit, retrace sentinel,
# static HBM budget — and asserts its harness coverage matches THIS
# registry exactly, so a new factory cannot land unanalyzed.
STEP_FACTORIES = {
    "vae": make_vae_train_step,
    "dalle": make_dalle_train_step,
    "dalle_sp": make_dalle_sp_train_step,
    "dalle_pp": make_dalle_pp_train_step,
    "clip": make_clip_train_step,
}
