"""dalle_pytorch_tpu — a TPU-native (JAX/XLA/Pallas/GSPMD) text-to-image
framework with the capabilities of NomadicDaggy/DALLE-pytorch.

Public surface mirrors the reference package exports
(`/root/reference/dalle_pytorch/__init__.py`): DALLE, CLIP, DiscreteVAE (+
pretrained VAE wrappers), plus the config/partitioning machinery that
replaces the reference's CUDA/DeepSpeed runtime.
"""

from .models.vae import DiscreteVAE, VAEConfig
from .models.dalle import DALLE, DALLEConfig
from .models.clip import CLIP, CLIPConfig
from .models.pretrained_vae import OpenAIDiscreteVAE, VQGanVAE1024

__version__ = "0.1.0"

__all__ = [
    "DiscreteVAE", "VAEConfig",
    "DALLE", "DALLEConfig",
    "CLIP", "CLIPConfig",
    "OpenAIDiscreteVAE", "VQGanVAE1024",
]
