"""graftscale: ledger-driven fleet autoscaler + brownout ladder (§22).

The fleet (§17/§21) can lose replicas and migrate work, but its capacity
is static — overload is answered only by shedding.  :class:`AutoScaler`
closes the loop over a live :class:`~.router.FleetRouter` using signals
that all already exist:

* per-SLO-class queue depth (``GenerationServer.backlog()``, cached for
  remote replicas via the graftwire heartbeat),
* the router audit ledger's shed rate (delta between evaluations),
* per-replica HBM headroom (the serve-steady mem watermark), and
* the perf ledger's ``predicted_bytes_per_token`` — affordable capacity
  is ``headroom ÷ (predicted per-slot bytes × slots)``, so every
  scale-up decision **cites the ledger fingerprint**, not a guess.

Every evaluation produces one typed :class:`Decision` emitted to
telemetry (kind ``autoscale``/``decision``) naming the action, the
brownout level, and the full :class:`Signals` snapshot it was computed
from.  Actuation is the fleet's existing machinery: scale-up spawns via
a caller-supplied ``spawn_fn`` (``remote.spawn_replica``) and warm-joins
the hash ring; scale-down rides the drain/rc-74 grace path.  Hysteresis
— separate up/down cooldowns, a max step, and a reversal ("flap")
counter with damping — keeps oscillating load from thrashing the ring.

Between healthy and shed sits the **brownout ladder**: ordered,
reversible :class:`DegradeLevel` rungs applied fleet-wide when the fleet
is saturated at ``max_replicas`` (or headroom-limited) and overload
persists — disable spec decode, tighten throughput-class admission,
shed throughput entirely, finally shed latency — and restored rung by
rung, in reverse, once the fleet is calm.  Spec decode is bit-exact
versus greedy (graftspec), so rung 1 trades only throughput; rungs 2-4
act through :meth:`FleetRouter.set_shed_factors`, so demoted classes
fail FAST with a typed :class:`~.router.ShedError` instead of timing
out.

The autoscaler survives its own faults: a spawn that never reaches the
ready-file handshake raises a typed :class:`~.remote.SpawnFailed` (the
child is killed and reaped), failures back off exponentially and are
budget-bounded; and a restarted autoscaler recomputes its world — the
current brownout level included — from ``router.audit()``, the shed
factors, and replica states (:meth:`AutoScaler.resync`): NO state is
persisted anywhere.
"""
from __future__ import annotations

import collections
import dataclasses
import enum
import math
import threading
import time
from typing import Callable, Deque, Dict, List, Mapping, Optional

from ..obs import metrics as obs_metrics
from ..obs import telemetry
from ..utils import locks
from .remote import SpawnFailed
from .replica import DRAINING, JOINING, SERVING
from .scheduler import LATENCY, SLO_CLASSES, THROUGHPUT

__all__ = ["AutoScaler", "Decision", "DegradeLevel", "ScalePolicy",
           "Signals", "SpawnFailed"]


class DegradeLevel(enum.IntEnum):
    """The brownout ladder, mildest rung first.  Rungs are CUMULATIVE
    (level N implies every rung <= N) and strictly reversible — restore
    walks back one rung at a time with its own hysteresis."""

    HEALTHY = 0           # full service: spec decode on, normal admission
    NO_SPEC = 1           # disable self-speculative decode fleet-wide
    TIGHT_THROUGHPUT = 2  # throughput admission bound 4.0x -> 1.0x slots
    SHED_THROUGHPUT = 3   # shed ALL throughput-class admissions
    SHED_LATENCY = 4      # shed latency too: the rung before falling over


@dataclasses.dataclass(frozen=True)
class Signals:
    """One observation of the fleet — everything a decision may cite.
    Pure data: the decision-table tests build these directly, the live
    loop fills them from the router + replica scale_signals()."""

    queued: Mapping[str, int]            # fleet queue depth per SLO class
    running: int = 0                     # occupied slots fleet-wide
    serving: int = 1                     # replicas in SERVING
    joining: int = 0                     # spawned, still warming
    draining: int = 0                    # retiring (capacity leaving)
    shed_delta: int = 0                  # sheds since last evaluation
    submitted_delta: int = 0             # submits since last evaluation
    headroom_bytes: Optional[int] = None  # min per-replica HBM headroom
    predicted_bytes_per_token: int = 0   # ledger per-slot byte stream
    ledger_fingerprint: str = ""         # the row the capacity math cites
    slots_per_replica: int = 2
    outstanding: int = 0                 # router futures not yet resolved

    @property
    def queued_total(self) -> int:
        return sum(self.queued.values())

    @property
    def demand_slots(self) -> int:
        """Slots the offered load wants RIGHT NOW: everything queued
        plus everything running."""
        return self.queued_total + self.running


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """The control law's knobs.  Defaults are the CI chaos-gate shape;
    production tunes cooldowns up by an order of magnitude."""

    min_replicas: int = 1
    max_replicas: int = 4
    # desired = ceil(demand_slots / (slots_per_replica * utilization)):
    # aim to run replicas at 75% so one replica's death has somewhere
    # to migrate to
    target_utilization: float = 0.75
    up_cooldown_s: float = 1.0         # min gap between scale-ups
    down_cooldown_s: float = 6.0       # min gap before ANY scale-down
    down_after: int = 3                # consecutive below-evals required
    max_step: int = 2                  # replicas added/retired per decision
    flap_window_s: float = 30.0        # reversal-counting window
    max_flaps: int = 2                 # reversals tolerated before damping
    degrade_after: int = 2             # overloaded evals before a new rung
    restore_after: int = 3             # calm evals before stepping back
    tight_throughput_factor: float = 1.0  # rung-2 throughput shed factor
    spawn_budget: int = 3              # consecutive SpawnFailed tolerated
    spawn_backoff_s: float = 0.5       # base backoff after a SpawnFailed


@dataclasses.dataclass(frozen=True)
class Decision:
    """One evaluation's typed outcome.  ``as_record()`` is the telemetry
    payload — flat, with every input signal and the ledger fingerprint,
    so the merged fleet stream can replay WHY each action happened."""

    action: str               # hold | scale_up | scale_down | degrade | restore
    target: int               # desired replica count (post-clamp)
    step: int                 # replicas to add (+) / retire (-) now
    level: DegradeLevel       # brownout level AFTER this decision
    reason: str
    saturated: bool           # pinned at max_replicas and still overloaded
    flaps: int                # reversals inside the flap window
    signals: Signals

    def as_record(self) -> dict:
        s = self.signals
        return dict(
            action=self.action, target=self.target, step=self.step,
            level=int(self.level), level_name=self.level.name,
            reason=self.reason, saturated=int(self.saturated),
            flaps=self.flaps,
            queued_latency=s.queued.get(LATENCY, 0),
            queued_throughput=s.queued.get(THROUGHPUT, 0),
            running=s.running, serving=s.serving, joining=s.joining,
            draining=s.draining, shed_delta=s.shed_delta,
            submitted_delta=s.submitted_delta,
            headroom_bytes=s.headroom_bytes,
            predicted_bytes_per_token=s.predicted_bytes_per_token,
            ledger_fingerprint=s.ledger_fingerprint,
            slots_per_replica=s.slots_per_replica,
            outstanding=s.outstanding)


class AutoScaler:
    """The control loop.  ``decide()`` is the pure core (signals in,
    :class:`Decision` out, only scalar control state touched) — the
    decision-table tests drive it with hand-built :class:`Signals` and
    explicit clocks, no processes or sockets.  ``step_once()`` is one
    full pass (collect → decide → emit → actuate); ``start()`` runs it
    on a daemon thread every ``interval_s``."""

    def __init__(self, router, spawn_fn: Optional[Callable] = None, *,
                 policy: Optional[ScalePolicy] = None,
                 interval_s: float = 0.5, name_prefix: str = "as",
                 time_fn=time.monotonic):
        self.router = router
        self.spawn_fn = spawn_fn
        self.policy = policy or ScalePolicy()
        self.interval_s = float(interval_s)
        self.name_prefix = str(name_prefix)
        self._time = time_fn
        self._lock = locks.TracedLock("autoscale")
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # --- control state: ALL of it recomputable.  resync() re-derives
        # the brownout level from the router and re-bases the audit
        # deltas; nothing below ever touches disk (restart contract d).
        self._level = DegradeLevel.HEALTHY
        self._last_scale_at = float("-inf")
        self._last_dir = 0                      # +1 up / -1 down / 0 never
        self._flips: Deque[float] = collections.deque()
        self._below_evals = 0                   # consecutive desired<current
        self._overload_evals = 0
        self._calm_evals = 0
        self._last_audit = {"shed": 0, "submitted": 0}
        self._spawn_fails = 0
        self._spawn_ok_at = float("-inf")       # backoff gate
        self._spawn_seq = 0
        self._budget_spent = False
        self._last_fingerprint = ""             # survives serving gaps
        self.spawned: List = []                 # replicas this loop spawned
        self.decisions: List[Decision] = []
        self.spawn_failures = 0                 # lifetime SpawnFailed count

    # --- lifecycle ----------------------------------------------------------

    @property
    def level(self) -> DegradeLevel:
        with self._lock:
            return self._level

    def start(self) -> "AutoScaler":
        assert self._thread is None, "autoscaler already started"
        self.resync()
        self._thread = threading.Thread(target=self._loop,
                                        name="graftscale", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.step_once()
            # graftlint: disable=EXC001 (the control loop must survive any single evaluation error; it is reported in-band as an autoscale event and the next tick retries)
            except Exception as e:
                telemetry.emit("autoscale", "loop_error", error=repr(e))

    def resync(self) -> None:
        """Recompute world state from the live router — the restart
        contract: a fresh autoscaler over an already-degraded fleet must
        resume the ladder where its predecessor left it, from nothing
        but the router's own observable state."""
        level = DegradeLevel.HEALTHY
        factors = self.router.shed_factors()
        if factors.get(LATENCY, 1.0) <= 0.0:
            level = DegradeLevel.SHED_LATENCY
        elif factors.get(THROUGHPUT, 0.0) <= 0.0:
            level = DegradeLevel.SHED_THROUGHPUT
        elif (factors.get(THROUGHPUT, 0.0)
              <= self.policy.tight_throughput_factor):
            level = DegradeLevel.TIGHT_THROUGHPUT
        else:
            # rung 1 leaves the router untouched; read it off the
            # replicas themselves (spec capable but toggled off)
            for sig in self._replica_signals():
                if sig.get("spec_capable") and not sig.get("spec"):
                    level = DegradeLevel.NO_SPEC
                    break
        a = self.router.audit()
        with self._lock:
            self._level = level
            self._last_audit = {"shed": a["shed"],
                                "submitted": a["submitted"]}
        telemetry.emit("autoscale", "resync", level=int(level),
                       level_name=level.name, shed=a["shed"],
                       submitted=a["submitted"],
                       outstanding=a["outstanding"])

    # --- observation --------------------------------------------------------

    def _replica_signals(self) -> List[dict]:
        out = []
        for r in self.router.replicas():
            if r.state != SERVING:
                continue
            scale_signals = getattr(r.server, "scale_signals", None)
            if scale_signals is None:
                continue
            out.append(scale_signals())
        return out

    def collect(self) -> Signals:
        """One fleet observation: replica states + cached scale signals
        + the audit ledger's deltas since the previous evaluation."""
        reps = self.router.replicas()
        serving = joining = draining = 0
        for r in reps:
            state = r.state
            if state == SERVING:
                serving += 1
            elif state == JOINING:
                joining += 1
            elif state == DRAINING:
                draining += 1
        queued = {slo: 0 for slo in SLO_CLASSES}
        running = 0
        headrooms: List[int] = []
        pbpt = 0
        fingerprint = ""
        for sig in self._replica_signals():
            for slo, n in sig.get("queued", {}).items():
                queued[slo] = queued.get(slo, 0) + int(n)
            running += int(sig.get("running", 0))
            if sig.get("headroom_bytes") is not None:
                headrooms.append(int(sig["headroom_bytes"]))
            pbpt = max(pbpt, int(sig.get("predicted_bytes_per_token", 0)))
            fingerprint = sig.get("ledger_fingerprint") or fingerprint
        audit = self.router.audit()
        with self._lock:
            shed_delta = audit["shed"] - self._last_audit["shed"]
            submitted_delta = (audit["submitted"]
                               - self._last_audit["submitted"])
            self._last_audit = {"shed": audit["shed"],
                                "submitted": audit["submitted"]}
            # the fingerprint is static per geometry: remember the last
            # live one so a decision taken in a no-serving-replica gap
            # (mid-migration) still cites the ledger row it scales for
            if fingerprint:
                self._last_fingerprint = fingerprint
            else:
                fingerprint = self._last_fingerprint
        return Signals(
            queued=queued, running=running, serving=serving,
            joining=joining, draining=draining,
            shed_delta=max(0, shed_delta),
            submitted_delta=max(0, submitted_delta),
            headroom_bytes=min(headrooms) if headrooms else None,
            predicted_bytes_per_token=pbpt,
            ledger_fingerprint=fingerprint,
            slots_per_replica=max((r.num_slots for r in reps), default=1),
            outstanding=audit["outstanding"])

    # --- the pure control law ----------------------------------------------

    def decide(self, signals: Signals, now: Optional[float] = None
               ) -> Decision:
        """Signals -> Decision.  Mutates only the scalar control state
        (cooldown clocks, flap window, rung counters) — never the fleet;
        :meth:`actuate` applies the returned decision."""
        now = self._time() if now is None else now
        p = self.policy
        with self._lock:
            decision = self._decide_locked(signals, now, p)
            self.decisions.append(decision)
        return decision

    def _decide_locked(self, s: Signals, now: float, p: ScalePolicy
                       ) -> Decision:
        spr = max(1, s.slots_per_replica)
        current = s.serving + s.joining   # capacity already on the way
        desired = max(1, math.ceil(
            s.demand_slots / (spr * p.target_utilization)))
        if s.shed_delta > 0:
            # shedding means admission is ALREADY refusing work: capacity
            # is short now regardless of what the queues sum to
            desired = max(desired, current + 1)
        want = desired                      # pre-clamp, for saturation
        desired = max(p.min_replicas, min(p.max_replicas, desired))

        # ledger-cited affordability: one more replica costs (per-slot
        # byte stream x slots) of headroom; unknown headroom (no
        # watermark yet / no device limit) skips the clamp
        headroom_limited = False
        if (desired > current and s.headroom_bytes is not None
                and s.predicted_bytes_per_token > 0):
            affordable = current + (s.headroom_bytes
                                    // (s.predicted_bytes_per_token * spr))
            if affordable < desired:
                headroom_limited = True
                desired = max(current, max(p.min_replicas, affordable))

        overloaded = (s.demand_slots > current * spr or s.shed_delta > 0)
        saturated = (overloaded and current >= p.max_replicas
                     and want > p.max_replicas)
        while self._flips and now - self._flips[0] > p.flap_window_s:
            self._flips.popleft()
        flaps = len(self._flips)

        # --- brownout ladder: rung transitions outrank scaling (undo
        # degradation before retiring capacity; degrade only when
        # scale-up has nowhere left to go)
        if (saturated or headroom_limited) and overloaded:
            self._overload_evals += 1
            self._calm_evals = 0
        elif not overloaded and s.shed_delta == 0 \
                and s.demand_slots <= current * spr:
            self._calm_evals += 1
            self._overload_evals = 0
        else:
            # overloaded but with somewhere to scale: not calm either —
            # an overload blip must reset the restore streak
            self._overload_evals = 0
            self._calm_evals = 0
        if (self._overload_evals >= p.degrade_after
                and self._level < DegradeLevel.SHED_LATENCY):
            self._level = DegradeLevel(self._level + 1)
            self._overload_evals = 0
            why = "headroom-limited" if headroom_limited else "saturated"
            return Decision(
                action="degrade", target=desired, step=0, level=self._level,
                reason=f"{why} at {current} replicas and still overloaded "
                       f"for {p.degrade_after} evals: brownout to "
                       f"{self._level.name}",
                saturated=saturated, flaps=flaps, signals=s)
        if (self._calm_evals >= p.restore_after
                and self._level > DegradeLevel.HEALTHY):
            self._level = DegradeLevel(self._level - 1)
            self._calm_evals = 0
            return Decision(
                action="restore", target=desired, step=0, level=self._level,
                reason=f"calm for {p.restore_after} evals: restore to "
                       f"{self._level.name}",
                saturated=saturated, flaps=flaps, signals=s)

        # --- scaling with hysteresis
        if desired > current:
            self._below_evals = 0
            if flaps >= p.max_flaps:
                return self._hold(s, desired, saturated, flaps,
                                  "flap-damped: "
                                  f"{flaps} reversals inside "
                                  f"{p.flap_window_s:g}s")
            if now - self._last_scale_at < p.up_cooldown_s:
                return self._hold(s, desired, saturated, flaps,
                                  "up-cooldown")
            step = min(desired - current, p.max_step)
            self._note_scale(now, +1)
            return Decision(
                action="scale_up", target=desired, step=step,
                level=self._level,
                reason=f"demand {s.demand_slots} slots > "
                       f"{current}x{spr} capacity"
                       + (f" (+{s.shed_delta} shed)" if s.shed_delta
                          else ""),
                saturated=saturated, flaps=len(self._flips), signals=s)
        if desired < current:
            self._below_evals += 1
            if flaps >= p.max_flaps:
                return self._hold(s, desired, saturated, flaps,
                                  "flap-damped: "
                                  f"{flaps} reversals inside "
                                  f"{p.flap_window_s:g}s")
            if self._below_evals < p.down_after:
                return self._hold(s, desired, saturated, flaps,
                                  f"below-target {self._below_evals}/"
                                  f"{p.down_after} evals")
            if now - self._last_scale_at < p.down_cooldown_s:
                return self._hold(s, desired, saturated, flaps,
                                  "down-cooldown")
            if s.draining > 0:
                return self._hold(s, desired, saturated, flaps,
                                  "drain already in flight")
            step = -min(current - desired, p.max_step)
            self._note_scale(now, -1)
            self._below_evals = 0
            return Decision(
                action="scale_down", target=desired, step=step,
                level=self._level,
                reason=f"demand {s.demand_slots} slots <= "
                       f"{desired}x{spr} capacity at "
                       f"{p.target_utilization:g} utilization",
                saturated=saturated, flaps=len(self._flips), signals=s)
        self._below_evals = 0
        return self._hold(s, desired, saturated, flaps, "at target")

    def _hold(self, s: Signals, target: int, saturated: bool, flaps: int,
              reason: str) -> Decision:
        return Decision(action="hold", target=target, step=0,
                        level=self._level, reason=reason,
                        saturated=saturated, flaps=flaps, signals=s)

    def _note_scale(self, now: float, direction: int) -> None:
        if self._last_dir != 0 and direction == -self._last_dir:
            self._flips.append(now)  # graftrace: unguarded (called only from _decide_locked, which always runs under the autoscale lock)
        self._last_dir = direction
        self._last_scale_at = now

    # --- actuation ----------------------------------------------------------

    def step_once(self) -> Decision:
        signals = self.collect()
        decision = self.decide(signals)
        self._emit_decision(decision)
        self.actuate(decision)
        return decision

    def _emit_decision(self, d: Decision) -> None:
        telemetry.emit("autoscale", "decision", **d.as_record())
        reg = obs_metrics.active()
        if reg is not None:
            reg.gauge("graft_autoscale_target",
                      "replica count the control law wants").set(d.target)
            reg.gauge("graft_autoscale_level",
                      "brownout ladder rung (0=healthy)").set(int(d.level))
            reg.gauge("graft_autoscale_flaps",
                      "scale-direction reversals in the flap window"
                      ).set(d.flaps)

    def actuate(self, decision: Decision) -> None:
        """Apply one decision to the fleet.  Runs OUTSIDE the control
        lock: spawn blocks on the ready handshake and drain/join take
        the router's lock."""
        if decision.action == "scale_up" and decision.step > 0:
            self._scale_up(decision.step)
        elif decision.action == "scale_down" and decision.step < 0:
            self._scale_down(-decision.step)
        elif decision.action in ("degrade", "restore"):
            self.apply_level(decision.level)

    def _next_name(self) -> str:
        taken = {r.name for r in self.router.replicas()}
        while True:
            with self._lock:
                self._spawn_seq += 1
                name = f"{self.name_prefix}{self._spawn_seq}"
            if name not in taken:
                return name

    def _scale_up(self, count: int) -> None:
        if self.spawn_fn is None:
            return
        p = self.policy
        for _ in range(count):
            now = self._time()
            with self._lock:
                blocked = self._budget_spent or now < self._spawn_ok_at
                budget_spent, fails = self._budget_spent, self._spawn_fails
            if blocked:
                telemetry.emit("autoscale", "spawn_deferred",
                               budget_spent=budget_spent, fails=fails)
                return
            name = self._next_name()
            try:
                replica = self.spawn_fn(name)
            except SpawnFailed as e:
                with self._lock:
                    self._spawn_fails += 1
                    self.spawn_failures += 1
                    fails = self._spawn_fails
                    self._spawn_ok_at = now + p.spawn_backoff_s * (
                        2 ** (fails - 1))
                    if fails > p.spawn_budget:
                        self._budget_spent = True
                telemetry.emit("autoscale", "spawn_failed", replica=name,
                               fails=fails, budget=p.spawn_budget,
                               budget_spent=fails > p.spawn_budget,
                               error=repr(e))
                reg = obs_metrics.active()
                if reg is not None:
                    reg.counter("graft_autoscale_spawn_failures_total",
                                "spawns that never reached ready").inc()
                return
            with self._lock:
                self._spawn_fails = 0
                degraded_spec = self._level >= DegradeLevel.NO_SPEC
            if degraded_spec:
                # a replica born into a brownout must join degraded
                self._set_replica_spec(replica, False)
            self.router.join(replica)
            self.spawned.append(replica)
            telemetry.emit("autoscale", "spawned", replica=name)

    def _scale_down(self, count: int) -> None:
        victims = sorted(
            (r for r in self.router.replicas() if r.state == SERVING),
            key=lambda r: (r.server.backlog()["queued_total"], r.name),
        )[:count]
        keep = self.policy.min_replicas
        serving = sum(1 for r in self.router.replicas()
                      if r.state == SERVING)
        for r in victims:
            if serving <= keep:
                return
            serving -= 1
            self.router.drain(r.name, reason="autoscale scale-down")
            telemetry.emit("autoscale", "retired", replica=r.name)

    def apply_level(self, level: DegradeLevel) -> None:
        """Project one ladder rung onto the fleet.  Idempotent: the full
        factor/spec state is recomputed from the rung, so re-applying
        (or applying after a resync) converges."""
        level = DegradeLevel(level)
        factors: Dict[str, float] = {}
        if level >= DegradeLevel.TIGHT_THROUGHPUT:
            factors[THROUGHPUT] = self.policy.tight_throughput_factor
        if level >= DegradeLevel.SHED_THROUGHPUT:
            factors[THROUGHPUT] = 0.0
        if level >= DegradeLevel.SHED_LATENCY:
            factors[LATENCY] = 0.0
        self.router.set_shed_factors(factors or None)
        spec_on = level < DegradeLevel.NO_SPEC
        for r in self.router.replicas():
            if r.state in (SERVING, JOINING):
                self._set_replica_spec(r, spec_on)
        with self._lock:
            self._level = level
        telemetry.emit("autoscale", "level_applied", level=int(level),
                       level_name=level.name, spec=spec_on,
                       factors=factors or None)

    def _set_replica_spec(self, replica, enabled: bool) -> None:
        set_spec = getattr(replica.server, "set_spec", None)
        if set_spec is None:
            return
        try:
            set_spec(bool(enabled))
        # graftlint: disable=EXC001 (a brownout toggle on a dying replica must not kill the ladder walk; the failure is reported in-band and the next apply_level converges)
        except Exception as e:
            telemetry.emit("autoscale", "spec_toggle_failed",
                           replica=replica.name, error=repr(e))
