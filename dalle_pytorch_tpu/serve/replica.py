"""Replica: one GenerationServer under a lifecycle state machine.

The fleet tier's unit of failure (DESIGN.md §17).  A single
``GenerationServer`` is one arena on one chip: when it dies, every
request it holds dies with it.  ``Replica`` wraps a server with the
state machine a router can reason about::

    JOINING ──warm──▶ SERVING ──drain──▶ DRAINING ──▶ DEAD
                         │                              ▲
                         └────────── died ──────────────┘

* **JOINING** — the driver thread is compiling the serve entry points
  (prefill/admit/tick) against a warmup prompt.  A joining replica takes
  no traffic: compiling on the first real request would hold that
  request (and the router's retry clock) for the whole compile.
* **SERVING** — the driver loop runs ``server.step()`` continuously,
  stamping a heartbeat (``last_beat``) every iteration.  The
  ``replica_down`` faultpoint fires here once per pass with ``step`` =
  the completed decode-tick count:
  ``replica_down:at_tick=N`` makes the thread *vanish* mid-decode — no
  cleanup, no future resolution — so the router's failure detectors
  (heartbeat staleness, :meth:`Replica.healthz`) are what find the
  corpse, exactly like a killed pod.
* **DRAINING** — the rc-74 preemption drill's shape applied to serving
  (utils/faults.py ``preempt``): the replica stops admitting
  (:meth:`begin_drain` evicts the queued backlog with a typed error the
  router resubmits elsewhere) and its running slots get the drain grace
  window to finish; :meth:`finish_drain` closes a clean drain,
  :meth:`halt` is the grace-expired hard kill that fails-and-migrates
  whatever is still running.  Either way nothing hangs.
* **DEAD** — terminal.  A rolled replica is replaced by a *new*
  ``Replica`` joining under traffic, never resurrected.

Every transition emits a ``replica.state`` graftscope event and updates
the one-hot ``graft_replica_state{replica,state}`` gauges, so
``obs_report --merge`` and ``monitor --fleet --metrics`` both see the
fleet's lifecycle.  When ``telemetry_dir`` is given the replica owns its
OWN ``Telemetry`` stream (one lane per replica in the merged fleet
report); its server emits serve events (submit/admit/tick/retire) into
the same lane.

Thread model: exactly one driver thread per replica (spawned by
:meth:`start`); the router calls ``server.submit`` from its own threads
(thread-safe) and the lifecycle methods from its monitor thread.
``halt``/``finish_drain`` join the driver before touching the server's
slot bookkeeping — ``GenerationServer.stop`` must not race a live
``step()``.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from ..obs import metrics as obs_metrics
from ..obs import telemetry
from ..obs.telemetry import Telemetry
from ..utils import faults
from ..utils import locks
from .scheduler import GenerationServer, ServerStopped

JOINING = "joining"
SERVING = "serving"
DRAINING = "draining"
DEAD = "dead"
STATES = (JOINING, SERVING, DRAINING, DEAD)


class ReplicaDown(ServerStopped):
    """Typed: the replica serving this request died, was halted, or was
    drained before the request finished — the router's retry path
    resubmits it elsewhere (the request replays deterministically from
    prefill: its key stream is pinned at submission)."""


class Replica:
    """One ``GenerationServer`` + driver thread + lifecycle state."""

    def __init__(self, name: str, dalle, variables, num_slots: int = 4, *,
                 telemetry_dir=None, host_index: int = 0,
                 warmup_text=None, idle_sleep_s: float = 0.001,
                 time_fn=time.monotonic, **server_kwargs):
        self.name = str(name)
        self._time = time_fn
        self._tel: Optional[Telemetry] = (
            Telemetry(telemetry_dir, host=host_index)
            if telemetry_dir is not None else None)
        self.server = GenerationServer(
            dalle, variables, num_slots, tel=self._tel,
            metrics_labels={"replica": self.name}, **server_kwargs)
        self.num_slots = int(num_slots)
        self.warmup_text = warmup_text
        self.idle_sleep_s = float(idle_sleep_s)
        self._state = JOINING
        self._state_lock = locks.TracedLock("replica.state")
        self.last_beat = self._time()
        self.ticks = 0        # driver loop passes (the heartbeat cadence)
        self.work_ticks = 0   # decode ticks that advanced a slot
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._announce(None, JOINING, "created")

    # --- state machine -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._state_lock:
            return self._state

    def _to(self, new: str, *, reason: str = "") -> None:
        with self._state_lock:
            old, self._state = self._state, new
        if old != new:
            self._announce(old, new, reason)

    def _announce(self, old: Optional[str], new: str, reason: str) -> None:
        self._emit("replica", "state", replica=self.name, frm=old, to=new,
                   reason=reason)
        reg = obs_metrics.active()
        if reg is not None:
            # one-hot across the state labels: a scraper reads the current
            # state as "the label whose gauge is 1" without diffing
            for s in STATES:
                reg.gauge("graft_replica_state",
                          "replica lifecycle state (one-hot per state)",
                          replica=self.name, state=s
                          ).set(1.0 if s == new else 0.0)

    def _emit(self, kind: str, name: str, **fields):
        if self._tel is not None:
            return self._tel.event(kind, name, **fields)
        return telemetry.emit(kind, name, **fields)

    # --- driver thread -----------------------------------------------------

    def start(self) -> "Replica":
        """Spawn the driver thread (JOINING → warm → SERVING)."""
        assert self._thread is None, f"replica {self.name} already started"
        self._thread = threading.Thread(
            target=self._run, name=f"replica-{self.name}", daemon=True)
        self._thread.start()
        return self

    def alive(self) -> bool:
        """True while the driver thread is running.  A replica whose
        thread died (kill, crash, injected ``replica_down``) reads False
        here even though its ``state`` may still say SERVING — the state
        is a claim, liveness is a fact, and the router trusts the fact."""
        return self._thread is not None and self._thread.is_alive()

    def beat_age(self) -> float:
        """Seconds since the driver loop last stamped its heartbeat."""
        return self._time() - self.last_beat

    def _warm(self) -> None:
        """Compile the serve entry points before taking traffic: one
        warmup request driven to completion (its result is discarded)."""
        if self.warmup_text is None:
            return
        h = self.server.submit(self.warmup_text)
        bound = 8 * self.server.arena.geometry.image_seq_len + 64
        steps = 0
        while not h.future.done() and not self._stop_evt.is_set():
            self.server.step()
            steps += 1
            assert steps < bound, "warmup request did not converge"

    def _run(self) -> None:
        try:
            self._warm()
            if self._stop_evt.is_set():
                return
            if self.state == JOINING:  # a drain can race the warmup
                self._to(SERVING, reason="warm")
            while not self._stop_evt.is_set():
                self.last_beat = self._time()
                self.ticks += 1
                # step coordinate = completed DECODE ticks, not loop
                # passes: an idle loop spins orders of magnitude faster
                # than it decodes, so `at_tick=N` pinned to loop passes
                # would fire before traffic ever arrived — the chaos spec
                # means "after the Nth decode tick", i.e. mid-stream
                if "at_tick" in faults.fire("replica_down",
                                            step=self.work_ticks):
                    # abrupt death: the thread vanishes mid-decode without
                    # failing its futures — detection is the ROUTER's job
                    # (heartbeat staleness / healthz), like a killed pod
                    return
                advanced = self.server.step()
                if advanced:
                    self.work_ticks += 1
                elif not self.server.busy:
                    if self._stop_evt.wait(self.idle_sleep_s):
                        break
        # graftlint: disable=EXC001 (driver thread of record: its death must land in the stream as an event; the router re-detects it via heartbeat staleness and migrates the futures)
        except BaseException as e:
            self._emit("replica", "driver_error", replica=self.name,
                       tick=self.ticks, error=repr(e))

    # --- probes ------------------------------------------------------------

    def healthz(self) -> dict:
        """The active-probe surface (in-process analog of GET /healthz).
        The ``replica_health`` faultpoint makes probe failures injectable
        while the driver keeps beating — the probe-without-heartbeat
        signal the router treats as a graceful quarantine, not a death."""
        try:
            faults.fire("replica_health")
        except faults.InjectedFault as e:
            return {"ok": False, "replica": self.name, "error": repr(e)}
        state = self.state
        return {"ok": self.alive() and state in (JOINING, SERVING, DRAINING),
                "replica": self.name, "state": state,
                "beat_age_s": round(self.beat_age(), 3),
                "ticks": self.ticks, "work_ticks": self.work_ticks,
                **self.server.backlog()}

    # --- drain / halt ------------------------------------------------------

    def begin_drain(self, *, reason: str = "drain"):
        """Stop admitting and evict the queued backlog, each failed with
        :class:`ReplicaDown` (the router resubmits them elsewhere).
        Running slots keep decoding toward the grace deadline the router
        accounts.  Returns the evicted handles."""
        self._to(DRAINING, reason=reason)
        return self.server.evict_queued(ReplicaDown(
            f"replica {self.name} draining ({reason}): request migrated"))

    def finish_drain(self, *, join_timeout_s: float = 5.0):
        """Clean drain completion: the running slots finished inside the
        grace window.  Stops the driver and goes DEAD with nothing left
        in flight (returns [] on a truly clean drain)."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
        left = self.server.stop(ReplicaDown(
            f"replica {self.name}: stopped at drain completion"))
        self._to(DEAD, reason="drained")
        return left

    def halt(self, error: Optional[BaseException] = None, *,
             join_timeout_s: float = 5.0):
        """Hard stop: the grace window expired, or the router declared
        this replica dead.  Stops the driver (if it still runs), fails
        every in-flight future with a typed error so the router migrates
        them, and goes DEAD.  Returns the unfinished handles."""
        self._stop_evt.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=join_timeout_s)
        unfinished = self.server.stop(
            error if error is not None
            else ReplicaDown(f"replica {self.name} halted"))
        self._to(DEAD, reason="halt")
        return unfinished

    def close(self) -> None:
        """Release the replica's own telemetry stream (if any)."""
        if self._tel is not None:
            self._tel.close()
