"""GenerationServer: iteration-level scheduling over the slot arena.

The host half of continuous batching (the device half is
``serve/engine.py``).  One scheduler iteration (:meth:`GenerationServer.
step`) is:

1. **retire** — slots whose request decoded its last token are fetched to
   host, their futures resolved, the slot freed;
2. **admit** — queued requests are prefilled (batch 1) and written into
   free slots, latency-class first.  When the latency queue is non-empty
   and no slot is free, the least-progressed *throughput*-class running
   request is **preempted**: its slot is reclaimed for the latency request
   and it re-queues at the front of the throughput queue (restarting from
   prefill — its key replays, so the restart is deterministic).  Latency
   requests never preempt each other;
3. **tick** — one jitted decode step advances every occupied slot.

Requests enter through the thread-safe :meth:`GenerationServer.submit`,
which returns a :class:`ServeHandle` carrying a ``concurrent.futures.
Future`` (``asyncio`` callers wrap it with ``asyncio.wrap_future``).  The
driving loop (:meth:`run_until_idle`, or :meth:`drive` for an open-loop
arrival trace) runs in whatever thread the caller owns — tests and
``bench_serve`` drive it synchronously for determinism; a daemon thread
calling ``step()`` is the serve-forever deployment shape.

Fault injection: every occupied slot hits the ``serve_request`` faultpoint
once per tick (``GRAFT_FAULTS="serve_request:fail_after=N"``), so a
mid-decode request failure is rehearsable: the failed request's future
carries the fault, its slot frees the same iteration, and co-batched
requests are untouched (tests/test_serve.py pins this).

SLO accounting per request: queue wait (submit -> last admit), decode time
(last admit -> finish), end-to-end latency, preemption count.
:meth:`stats` aggregates p50/p99 latency, occupancy, and decoded-token
throughput — the ``bench_serve`` row schema (PERF.md).

Lifecycle: :meth:`GenerationServer.evict_queued` (stop admitting, fail
the queued backlog typed — the drain-migration half) and
:meth:`GenerationServer.stop` (fail everything in flight typed) uphold
the no-hung-future contract the fleet tier (serve/replica.py +
serve/router.py) is built on: a future handed out by ``submit`` ALWAYS
resolves — with codes or with a typed error — whatever happens to the
server behind it.
"""
from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import threading
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..obs import mem as obs_mem
from ..obs import metrics as obs_metrics
from ..obs import telemetry
from ..utils import faults
from ..utils import locks
from .engine import SlotArena
from .prefix import RadixPrefixCache

LATENCY = "latency"
THROUGHPUT = "throughput"
SLO_CLASSES = (LATENCY, THROUGHPUT)


class ServerStopped(RuntimeError):
    """Typed terminal error for a request a server will never finish: the
    server stopped (or started draining) with the request still queued or
    mid-decode.  The future RESOLVES with this — a caller blocked on
    ``handle.result()`` gets an exception immediately instead of hanging
    forever on a decode that will never run; a fleet router treats it as
    the retry-elsewhere signal (serve/router.py)."""


@dataclasses.dataclass
class ServeHandle:
    """One submitted request: its future plus the SLO bookkeeping."""

    request_id: int
    slo: str
    temperature: float
    text: np.ndarray                       # [1, text_seq_len] int32
    key: np.ndarray                        # [2] uint32 — replays on restart
    future: concurrent.futures.Future = dataclasses.field(
        default_factory=concurrent.futures.Future)
    submitted_at: float = 0.0
    admitted_at: Optional[float] = None    # last admission (post-preemption)
    finished_at: Optional[float] = None
    preemptions: int = 0

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Decoded image codes [image_seq_len]; raises the request's
        failure (e.g. an injected fault).  Only returns once the driving
        loop has retired the request — call from a different thread than
        the one stepping the server, or after ``run_until_idle``."""
        return self.future.result(timeout)

    @property
    def latency(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at


@dataclasses.dataclass
class _Running:
    handle: ServeHandle
    done: int  # codes decoded so far (admit samples the first)
    # prompt-token key pinning this request's prefix-cache payload
    # (None when the cache is off); released on retire/fail/preempt/stop
    prefix_key: Optional[Tuple[int, ...]] = None


class GenerationServer:
    """Continuous-batching generation service over one DALLE model."""

    def __init__(self, dalle, variables, num_slots: int = 8, *,
                 filter_thres: float = 0.9, top_p: Optional[float] = None,
                 seed: int = 0, time_fn=time.monotonic,
                 slo_targets: Optional[Dict[str, float]] = None,
                 tick_sample: int = 1, tel=None,
                 metrics_labels: Optional[Dict[str, str]] = None,
                 mem_watermark_ticks: int = 256,
                 mem_hbm_bytes: Optional[int] = None,
                 prefix_cache: bool = False, prefix_capacity: int = 32):
        self.arena = SlotArena(dalle, variables, num_slots,
                               filter_thres=filter_thres, top_p=top_p)
        # spec_decode (a model-plan flag, default OFF): the scheduler's
        # only change is variable tokens-per-tick — tick_spec returns each
        # slot's accepted span length m and `done`/token accounting add m
        # instead of 1.  SLO/latency math is untouched (it is per-request
        # wall-clock, not per-tick).
        # _spec_capable pins what the model plan compiled; _spec is the
        # RUNTIME toggle (the graftscale brownout ladder's rung 1 —
        # set_spec()), never exceeding capability
        self._spec_capable = bool(dalle.cfg.spec_decode)
        self._spec = self._spec_capable
        self._spec_committed = 0
        # prefix_cache (a server knob, default OFF): admissions sharing a
        # prompt install copies of ONE batch-1 prefill via the refcounted
        # radix tree — including identical prompts already sitting in the
        # queue together (the dedupe case: the first admit misses and
        # inserts, the rest hit before any tick runs).
        self.prefix: Optional[RadixPrefixCache] = None
        if prefix_cache:
            from ..utils.profiling import dalle_prefill_flops
            self.prefix = RadixPrefixCache(
                prefix_capacity,
                prefill_flops=dalle_prefill_flops(dalle.cfg))
        self.prefill_count = 0  # arena.prefill CALLS (cache hits skip it)
        # tel: an explicit obs.telemetry.Telemetry instance to emit into
        # (a fleet replica's own per-stream lane); None = the module
        # singleton, the single-server deployment shape.  metrics_labels
        # ride every direct-instrumented series (e.g. {"replica": "r0"})
        # so N servers in one process don't clobber one another's gauges;
        # the default empty dict keeps the legacy series names bit-for-bit.
        self._tel = tel
        self._metrics_labels = dict(metrics_labels or {})
        self.num_slots = num_slots
        # the cost-model's HBM stream per decoded token for THIS arena
        # (cache payload + int8 scale planes, matching
        # profiling.dalle_decode_cache_bytes) — static per server, joined
        # against measured tok/s by monitor --fleet / graftprof --report
        from ..obs import prof
        self.predicted_bytes_per_token = prof.predicted_serve_bytes_per_token(
            dalle.cfg, num_slots)
        # the ledger row this arena's capacity math cites: graftscale
        # decision records carry it so "why did we scale" is answerable
        # from the stream alone (DESIGN.md §22)
        self.ledger_fingerprint = prof.row_fingerprint(
            prof.fingerprint_payload(dalle.cfg, target="serve",
                                     slots=int(num_slots)))
        # last serve-steady headroom watermark (None until the first
        # mem poll lands, or when the backend reports no byte limit)
        self.last_headroom_bytes: Optional[int] = None
        reg = obs_metrics.active()
        if reg is not None:
            reg.gauge("graft_serve_predicted_bytes_per_token",
                      "cost-model HBM bytes per decoded token",
                      **self._metrics_labels
                      ).set(self.predicted_bytes_per_token)
        # telemetry tick sampling: emit one aggregate `serve tick` record
        # per `tick_sample` decode ticks instead of 1:1 — a week-long serve
        # process at ~10ms/tick writes ~8.6M tick records a day unsampled.
        # The aggregate CARRIES the skipped ticks' stats (ticks covered,
        # summed/min/max active slots, covered clock range), so stream
        # consumers (obs/report.py) reconstruct totals exactly; partial
        # windows flush when the server drains idle, so nothing is lost.
        self.tick_sample = max(1, int(tick_sample))
        self._tick_agg = {"ticks": 0, "tokens": 0, "active_sum": 0,
                          "active_min": None, "active_max": 0,
                          "clock_first": None}
        # serve-steady memory watermarks: one obs/mem poll per
        # `mem_watermark_ticks` decode ticks (0 disables).  The tracker
        # owns the repo's managed polling surface (MEM001); emit=False
        # because the record must ride THIS server's lane (self._emit),
        # not the module singleton — and the replica-labeled headroom
        # gauge is set here so monitor --fleet can print it per replica.
        # mem_hbm_bytes pins the headroom denominator where the backend
        # reports no bytes_limit (CPU CI, the chaos rows) — on a real
        # chip leave it None and the device limit is used.
        self.mem_watermark_ticks = max(0, int(mem_watermark_ticks))
        self.mem_tracker = obs_mem.MemTracker(hbm_bytes=mem_hbm_bytes,
                                              emit=False)
        self._ticks_since_watermark = 0
        # optional end-to-end latency targets (seconds) per SLO class:
        # when set, each retirement records slo_ok and stats()/obs_report
        # aggregate attainment per class
        self.slo_targets = dict(slo_targets or {})
        self._time = time_fn
        self._seed = seed
        self._lock = locks.TracedLock("scheduler")
        self._queues: Dict[str, Deque[ServeHandle]] = {
            LATENCY: collections.deque(), THROUGHPUT: collections.deque()}
        self._running: Dict[int, _Running] = {}       # slot -> running
        self._free: List[int] = list(range(num_slots))
        self._next_id = 0
        self._stopped = False
        self._draining = False
        self.completed: List[ServeHandle] = []
        self.failed: List[ServeHandle] = []
        self.preemption_count = 0
        self._ticks = 0
        self._clock = 0   # arena tick counter: the phase-aligned write column
        self._occupied_slot_ticks = 0
        self._decoded_tokens = 0

    # --- telemetry plumbing -------------------------------------------------

    def _emit(self, kind: str, name: str, **fields):
        """Emit into this server's own stream when one was given (the
        fleet tier: one lane per replica), else the module singleton."""
        if self._tel is not None:
            return self._tel.event(kind, name, **fields)
        return telemetry.emit(kind, name, **fields)

    def _span(self, kind: str, name: str, **fields):
        if self._tel is not None:
            return self._tel.span(kind, name, **fields)
        return telemetry.span(kind, name, **fields)

    # --- submission --------------------------------------------------------

    def submit(self, text, *, slo: str = THROUGHPUT,
               temperature: float = 1.0,
               key: Optional[np.ndarray] = None) -> ServeHandle:
        """Queue one request (thread-safe).  ``text`` is [text_seq_len] or
        [1, text_seq_len] int32 tokens; ``key`` overrides the per-request
        rng key (default: derived from (server seed, request id), so every
        request owns an independent deterministic stream)."""
        if slo not in SLO_CLASSES:
            raise ValueError(f"unknown SLO class {slo!r}; one of {SLO_CLASSES}")
        text = np.asarray(text, np.int32)
        if text.ndim == 1:
            text = text[None]
        assert text.shape[0] == 1, (
            f"one prompt per request; got batch {text.shape[0]}")
        with self._lock:
            if self._stopped or self._draining:
                # typed refusal, never a queued future nobody will serve:
                # a router that raced a drain/stop retries elsewhere
                raise ServerStopped(
                    "server is "
                    + ("stopped" if self._stopped else "draining")
                    + "; not admitting new requests")
            rid = self._next_id
            self._next_id += 1
            handle = ServeHandle(
                request_id=rid, slo=slo, temperature=float(temperature),
                text=text,
                key=(np.asarray(key, np.uint32) if key is not None
                     else np.asarray([self._seed, rid], np.uint32)),
                submitted_at=self._time())
            self._queues[slo].append(handle)
            depth = len(self._queues[slo])
        self._emit("serve", "submit", rid=rid, slo=slo)
        # queue depth is THE admission-feedback signal a front-end router
        # consumes (per-replica load); direct-instrumented (not derived
        # from events) so it works with telemetry off and never lags
        reg = obs_metrics.active()
        if reg is not None:
            reg.gauge("graft_serve_queue_depth",
                      "queued requests awaiting a slot", slo=slo,
                      **self._metrics_labels).set(depth)
        return handle

    # --- scheduler iteration ----------------------------------------------

    @property
    def busy(self) -> bool:
        with self._lock:
            return bool(self._running) or any(self._queues.values())

    def step(self, tick: bool = True) -> int:
        """One scheduler iteration: retire, admit, and (unless
        ``tick=False`` — the warm-the-batch move tests use) one decode
        tick.  Returns the number of slots that advanced."""
        self._retire_finished()
        self._admit_pending()
        if not tick:
            return 0
        advanced = self._tick_once()
        if advanced == 0:
            # drained idle: flush the partial sampling window so the
            # stream's aggregates cover every tick that actually ran
            self._flush_tick_agg()
        return advanced

    def run_until_idle(self, max_ticks: Optional[int] = None) -> None:
        """Drive until every queued/running request finishes (or fails)."""
        ticks = 0
        while self.busy:
            advanced = self.step()
            ticks += 1
            if advanced == 0 and not self.busy:
                break
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(
                    f"server not idle after {max_ticks} ticks: "
                    f"{len(self._running)} running, "
                    f"{self.backlog()['queued_total']} queued")

    def drive(self, arrivals: Sequence[Tuple[float, dict]],
              max_ticks: Optional[int] = None) -> dict:
        """Open-loop trace: ``arrivals`` is [(offset_seconds, submit_kwargs)]
        relative to the call.  Requests are submitted when the clock passes
        their offset — never gated on service progress (open loop: the
        queue grows if the server can't keep up, exactly like production
        ingress).  Returns :meth:`stats` over the drive window."""
        t0 = self._time()
        pending = sorted(arrivals, key=lambda a: a[0])
        i = 0
        ticks = 0
        tokens0 = self._decoded_tokens
        while i < len(pending) or self.busy:
            now = self._time() - t0
            while i < len(pending) and pending[i][0] <= now:
                self.submit(**pending[i][1])
                i += 1
            if not self.busy:
                # idle gap before the next arrival: jump the open loop
                # forward instead of busy-waiting on the clock
                time.sleep(min(0.001, max(0.0, pending[i][0] - now)))  # graftlint: disable=THR002 (open-loop trace pacing against the local clock — the wake condition is wall time reaching the next arrival offset, not shared state, and drive() runs on the single driver thread with nothing to stop early for)
                continue
            self.step()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(f"drive exceeded {max_ticks} ticks")
        dt = self._time() - t0
        return self.stats(window_seconds=dt,
                          window_tokens=self._decoded_tokens - tokens0)

    # --- internals ---------------------------------------------------------

    def _retire_finished(self) -> None:
        total = self.arena.geometry.image_seq_len
        for slot in sorted(self._running):
            run = self._running[slot]
            if run.done >= total:
                codes = self.arena.fetch_codes(slot)
                h = run.handle
                h.finished_at = self._time()
                del self._running[slot]
                self._free.append(slot)
                if self.prefix is not None and run.prefix_key is not None:
                    self.prefix.release(run.prefix_key)
                self.completed.append(h)
                target = self.slo_targets.get(h.slo)
                self._emit(
                    "serve", "retire", rid=h.request_id, slot=slot,
                    slo=h.slo, tokens=run.done, latency_s=h.latency,
                    queue_wait_s=(h.admitted_at - h.submitted_at
                                  if h.admitted_at is not None else None),
                    decode_s=(h.finished_at - h.admitted_at
                              if h.admitted_at is not None else None),
                    preemptions=h.preemptions,
                    slo_ok=(None if target is None or h.latency is None
                            else bool(h.latency <= target)))
                reg = obs_metrics.active()
                if reg is not None and h.latency is not None:
                    reg.histogram("graft_serve_latency_seconds",
                                  "end-to-end request latency", slo=h.slo,
                                  **self._metrics_labels).observe(h.latency)
                    reg.counter("graft_serve_retired_total",
                                "completed requests", slo=h.slo,
                                **self._metrics_labels).inc()
                    if target is not None:
                        reg.counter(
                            "graft_serve_slo_total",
                            "retirements by SLO verdict", slo=h.slo,
                            ok=str(bool(h.latency <= target)).lower(),
                            **self._metrics_labels).inc()
                h.future.set_result(codes)

    def _fail(self, slot: int, exc: BaseException) -> None:
        run = self._running.pop(slot)
        self._free.append(slot)
        if self.prefix is not None and run.prefix_key is not None:
            self.prefix.release(run.prefix_key)
        run.handle.finished_at = self._time()
        self.failed.append(run.handle)
        self._emit("serve", "fail", rid=run.handle.request_id, slot=slot,
                   slo=run.handle.slo, tokens=run.done, error=repr(exc))
        run.handle.future.set_exception(exc)

    def _preempt_one_throughput(self) -> Optional[int]:
        """Reclaim the least-progressed throughput-class slot for a
        waiting latency request; its request restarts from prefill at the
        front of the throughput queue.  None when nothing is preemptible
        (every running request is latency-class)."""
        victims = [(run.done, slot) for slot, run in self._running.items()
                   if run.handle.slo == THROUGHPUT]
        if not victims:
            return None
        _, slot = min(victims)
        run = self._running.pop(slot)
        self._free.append(slot)
        if self.prefix is not None and run.prefix_key is not None:
            # unpin now; the restart's admit re-acquires (likely a hit —
            # the payload stays resident unless eviction claims it)
            self.prefix.release(run.prefix_key)
        run.handle.preemptions += 1
        self.preemption_count += 1
        self._emit("serve", "preempt", rid=run.handle.request_id,
                   slot=slot, tokens=run.done,
                   preemptions=run.handle.preemptions)
        with self._lock:
            self._queues[THROUGHPUT].appendleft(run.handle)
        return slot

    def _admit_pending(self) -> None:
        while True:
            with self._lock:
                want_latency = bool(self._queues[LATENCY])
            if want_latency and not self._free:
                if self._preempt_one_throughput() is None:
                    break  # all slots latency-class: no preemption
            if not self._free:
                break
            with self._lock:
                for slo in SLO_CLASSES:  # latency first
                    if self._queues[slo]:
                        handle = self._queues[slo].popleft()
                        break
                else:
                    break
            self._admit(handle)

    def _admit(self, handle: ServeHandle) -> None:
        pkey: Optional[Tuple[int, ...]] = None
        payload = None
        if self.prefix is not None:
            pkey = tuple(int(t) for t in handle.text[0])
            payload = self.prefix.acquire(pkey)
        hit = payload is not None
        if payload is None:
            with self._span("serve", "prefill", rid=handle.request_id):
                payload = self.arena.prefill(jnp.asarray(handle.text))
            self.prefill_count += 1
            if self.prefix is not None:
                # insert pins for THIS request (and dedupes a racing
                # identical insert by keeping the resident payload)
                payload = self.prefix.insert(pkey, payload)
        first_logits, caches = payload
        slot = self._free.pop()
        # self._clock is the NEXT tick's number — it pins the slot's cache
        # rotation so every later tick writes the shared physical column
        self.arena.admit(slot, first_logits, caches, handle.key,
                         handle.temperature, self._clock)
        handle.admitted_at = self._time()
        self._emit("serve", "admit", rid=handle.request_id, slot=slot,
                   slo=handle.slo,
                   queue_wait_s=handle.admitted_at - handle.submitted_at,
                   preemptions=handle.preemptions)
        if self.prefix is not None:
            st = self.prefix.stats()
            self._emit("serve", "prefix", rid=handle.request_id, hit=hit,
                       entries=st["entries"],
                       flops_saved=st["prefill_flops_saved"])
        reg = obs_metrics.active()
        if reg is not None:
            with self._lock:
                depth = len(self._queues[handle.slo])
            reg.gauge("graft_serve_queue_depth",
                      "queued requests awaiting a slot",
                      slo=handle.slo, **self._metrics_labels).set(depth)
            if self.prefix is not None:
                if hit:
                    reg.counter("graft_serve_prefix_hits_total",
                                "admissions served from the prefix cache",
                                **self._metrics_labels).inc()
                    reg.counter("graft_serve_prefix_flops_saved_total",
                                "prefill FLOPs avoided by prefix hits",
                                **self._metrics_labels
                                ).inc(self.prefix.prefill_flops)
                else:
                    reg.counter("graft_serve_prefix_misses_total",
                                "admissions that ran a fresh prefill",
                                **self._metrics_labels).inc()
                reg.gauge("graft_serve_prefix_entries",
                          "resident prefix-cache payloads",
                          **self._metrics_labels
                          ).set(self.prefix.stats()["entries"])
        self._running[slot] = _Running(handle=handle, done=1,
                                       prefix_key=pkey)
        self._decoded_tokens += 1  # admit samples the request's first code

    def _tick_once(self) -> int:
        # the serve_request faultpoint: one hit per occupied slot per tick,
        # in slot order — an injected failure frees ITS slot and leaves
        # co-batched slots advancing this very tick
        for slot in sorted(self._running):
            try:
                faults.fire("serve_request",
                            step=self._running[slot].done)
            except faults.InjectedFault as e:
                self._fail(slot, e)
        # finished-but-unretired slots (possible only if a caller skips the
        # retire phase) must NOT advance: their output row is complete and
        # another tick would overwrite its clamped last position
        total = self.arena.geometry.image_seq_len
        advancing = [s for s, run in self._running.items()
                     if run.done < total]
        if not advancing:
            return 0
        mask = np.zeros((self.num_slots,), bool)
        for slot in advancing:
            mask[slot] = True
        if self._spec:
            # speculative tick: each active slot commits its accepted
            # span (1..spec_k tokens) — progress accounting consumes the
            # per-slot lengths, everything else (occupancy, SLO math) is
            # still per-tick/per-request
            ms = self.arena.tick_spec(mask)
            self._clock += 1
            tokens = 0
            for slot in advancing:
                adv = int(ms[slot])
                self._running[slot].done += adv
                tokens += adv
            self._spec_committed += tokens
        else:
            self.arena.tick(mask, self._clock)
            self._clock += 1
            for slot in advancing:
                self._running[slot].done += 1
            tokens = len(advancing)
        n = len(advancing)
        self._ticks += 1
        self._occupied_slot_ticks += n
        self._decoded_tokens += tokens
        # one record per `tick_sample` decode ticks (never per slot per
        # tick): occupancy and clock phase land on the timeline without
        # multiplying the stream by num_slots x tick rate
        agg = self._tick_agg
        agg["ticks"] += 1
        agg["tokens"] += tokens
        agg["active_sum"] += n
        agg["active_min"] = (n if agg["active_min"] is None
                             else min(agg["active_min"], n))
        agg["active_max"] = max(agg["active_max"], n)
        if agg["clock_first"] is None:
            agg["clock_first"] = self._clock - 1
        if agg["ticks"] >= self.tick_sample:
            self._flush_tick_agg()
        return n

    def _flush_tick_agg(self) -> None:
        """Emit the aggregate `serve tick` record for the covered window
        (1 tick at tick_sample=1 — the legacy 1:1 stream — or up to
        tick_sample skipped ticks' stats in one record)."""
        agg = self._tick_agg
        if not agg["ticks"]:
            return
        self._emit("serve", "tick", clock=self._clock - 1,
                   active=agg["active_sum"] / agg["ticks"],
                   ticks=agg["ticks"], active_sum=agg["active_sum"],
                   tokens=agg["tokens"],
                   active_min=agg["active_min"],
                   active_max=agg["active_max"],
                   clock_first=agg["clock_first"],
                   **({"spec": True} if self._spec else {}))
        reg = obs_metrics.active()
        if reg is not None:
            if self._spec and agg["active_sum"]:
                # measured accepted-K over the window: the cost model's
                # denominator (prof.predicted_spec_speedup), exported so
                # the A/B stage and monitor can join it live
                reg.gauge("graft_serve_spec_accepted_k",
                          "mean committed tokens per active slot-tick",
                          **self._metrics_labels
                          ).set(agg["tokens"] / agg["active_sum"])
            reg.gauge("graft_serve_occupancy",
                      "occupied-slot fraction over the last tick window",
                      **self._metrics_labels
                      ).set(agg["active_sum"]
                            / (agg["ticks"] * self.num_slots))
            reg.counter("graft_serve_ticks_total", "decode ticks run",
                        **self._metrics_labels).inc(agg["ticks"])
            # re-assert the static byte-stream gauge here too: the
            # registry may have been installed after __init__ ran
            reg.gauge("graft_serve_predicted_bytes_per_token",
                      "cost-model HBM bytes per decoded token",
                      **self._metrics_labels
                      ).set(self.predicted_bytes_per_token)
        self._ticks_since_watermark += agg["ticks"]
        if (self.mem_watermark_ticks
                and self._ticks_since_watermark >= self.mem_watermark_ticks):
            self._emit_mem_watermark()
        self._tick_agg = {"ticks": 0, "tokens": 0, "active_sum": 0,
                          "active_min": None, "active_max": 0,
                          "clock_first": None}

    def _emit_mem_watermark(self) -> None:
        """One serve-steady memory poll: the watermark record rides this
        server's lane, and the headroom lands as a replica-labeled gauge
        (the series ``monitor --fleet`` prints beside the predicted byte
        stream)."""
        self._ticks_since_watermark = 0
        rec = self.mem_tracker.snapshot("serve_steady")
        self._emit("mem", "watermark", **rec)
        if rec.get("headroom_bytes") is not None:
            self.last_headroom_bytes = int(rec["headroom_bytes"])
        reg = obs_metrics.active()
        if reg is not None and rec.get("headroom_bytes") is not None:
            reg.gauge("graft_hbm_headroom_bytes",
                      "HBM bytes left under the device limit",
                      **self._metrics_labels).set(rec["headroom_bytes"])

    # --- lifecycle: drain / stop -------------------------------------------

    @property
    def stopped(self) -> bool:
        with self._lock:
            return self._stopped

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def _zero_queue_gauges(self) -> None:
        reg = obs_metrics.active()
        if reg is not None:
            for slo in SLO_CLASSES:
                reg.gauge("graft_serve_queue_depth",
                          "queued requests awaiting a slot", slo=slo,
                          **self._metrics_labels).set(0)

    def evict_queued(self, error: Optional[BaseException] = None
                     ) -> List[ServeHandle]:
        """Drain, step 1: refuse new admissions and fail every QUEUED (not
        yet admitted) request's future with a typed error — the
        migrate-the-backlog half of the drain protocol.  Running slots
        keep decoding: they either finish inside the drain grace window or
        are failed-and-migrated by :meth:`stop` when it closes.  Returns
        the evicted handles."""
        err = (error if error is not None
               else ServerStopped("request evicted: server draining"))
        with self._lock:
            self._draining = True
            evicted = [h for slo in SLO_CLASSES for h in self._queues[slo]]
            for q in self._queues.values():
                q.clear()
        for h in evicted:
            h.finished_at = self._time()
            self.failed.append(h)
            self._emit("serve", "evicted", rid=h.request_id, slo=h.slo,
                       error=repr(err))
        self._zero_queue_gauges()
        # exceptions are set OUTSIDE every lock: done-callbacks (a fleet
        # router's retry path) run synchronously on this thread and may
        # submit to OTHER servers
        for h in evicted:
            h.future.set_exception(err)
        return evicted

    def stop(self, error: Optional[BaseException] = None
             ) -> List[ServeHandle]:
        """Stop serving: fail EVERY queued and running request's future
        with a typed error (default :class:`ServerStopped`) so no caller
        blocks forever on a decode that will never run — the
        blocked-forever shutdown bug this method exists to close.  Later
        :meth:`submit` calls raise the same typed error immediately.

        Must be called from the driving thread, or after the driving loop
        has exited (a fleet replica joins its driver first) — it reclaims
        the running slots' bookkeeping.  Returns the unfinished handles;
        idempotent (a second stop returns [])."""
        err = (error if error is not None
               else ServerStopped("server stopped with requests in flight"))
        with self._lock:
            self._stopped = True
            self._draining = True
            unfinished = [h for slo in SLO_CLASSES
                          for h in self._queues[slo]]
            for q in self._queues.values():
                q.clear()
        for slot in sorted(self._running):
            run = self._running.pop(slot)
            self._free.append(slot)
            if self.prefix is not None and run.prefix_key is not None:
                self.prefix.release(run.prefix_key)
            unfinished.append(run.handle)
        for h in unfinished:
            h.finished_at = self._time()
            self.failed.append(h)
            self._emit("serve", "stopped", rid=h.request_id, slo=h.slo,
                       error=repr(err))
        self._flush_tick_agg()
        self._zero_queue_gauges()
        # same outside-the-lock discipline as evict_queued
        for h in unfinished:
            h.future.set_exception(err)
        return unfinished

    # --- metrics ------------------------------------------------------------

    def backlog(self) -> dict:
        """Cheap load feedback for a fleet router: queued requests per SLO
        class plus the running-slot count — no percentile math (that is
        :meth:`stats`), so it can be polled per routing decision."""
        with self._lock:
            queued = {slo: len(self._queues[slo]) for slo in SLO_CLASSES}
        return dict(queued=queued, queued_total=sum(queued.values()),
                    running=len(self._running))

    @property
    def spec_enabled(self) -> bool:
        return self._spec

    def set_spec(self, enabled: bool) -> bool:
        """Toggle self-speculative decode at the tick boundary — the
        brownout ladder's mildest rung (graftscale).  Effective only
        when the model plan compiled the spec entry points
        (``cfg.spec_decode``); returns the state actually in force.
        Safe mid-stream: spec commits are bit-identical to greedy
        (graftspec's acceptance rule), so flipping between ticks cannot
        change any decoded codes — only tokens-per-tick.  The flag is a
        plain bool store (the driver already reads it unlocked per
        tick); no lock is needed or taken."""
        want = bool(enabled) and self._spec_capable
        changed = want != self._spec
        self._spec = want
        if changed:
            self._emit("serve", "spec_toggle", enabled=want)
        return want

    def scale_signals(self) -> dict:
        """One autoscaler observation of THIS server: queue depth per
        class + running slots (the demand side), the last serve-steady
        headroom watermark + the ledger's per-slot byte stream and row
        fingerprint (the capacity side), and the spec-decode state (the
        brownout ladder's rung-1 readback).  Cheap enough to ride the
        graftwire heartbeat."""
        b = self.backlog()
        return dict(
            queued=b["queued"], running=b["running"],
            num_slots=self.num_slots,
            headroom_bytes=self.last_headroom_bytes,
            predicted_bytes_per_token=self.predicted_bytes_per_token,
            ledger_fingerprint=self.ledger_fingerprint,
            spec=self._spec, spec_capable=self._spec_capable)

    def trace_counts(self) -> dict:
        return self.arena.trace_counts()

    def stats(self, window_seconds: Optional[float] = None,
              window_tokens: Optional[int] = None) -> dict:
        """The bench_serve row: aggregate throughput, occupancy, latency
        percentiles per SLO class, preemptions, failures."""
        lat = {slo: sorted(h.latency for h in self.completed
                           if h.slo == slo and h.latency is not None)
               for slo in SLO_CLASSES}

        def pct(values, q):
            return float(np.percentile(values, q)) if values else None

        tokens = (window_tokens if window_tokens is not None
                  else self._decoded_tokens)
        self._flush_tick_agg()  # a stats() reader sees every tick covered

        def attainment(slo):
            target = self.slo_targets.get(slo)
            if target is None or not lat[slo]:
                return None
            return sum(v <= target for v in lat[slo]) / len(lat[slo])

        with self._lock:
            queue_depth = {slo: len(self._queues[slo])
                           for slo in SLO_CLASSES}
        return dict(
            ticks=self._ticks,
            decoded_tokens=tokens,
            predicted_bytes_per_token=self.predicted_bytes_per_token,
            queue_depth=queue_depth,
            tok_per_s=(tokens / window_seconds
                       if window_seconds else None),
            occupancy=(self._occupied_slot_ticks
                       / (self._ticks * self.num_slots)
                       if self._ticks else 0.0),
            completed=len(self.completed),
            failed=len(self.failed),
            preemptions=self.preemption_count,
            latency_p50={slo: pct(lat[slo], 50) for slo in SLO_CLASSES},
            latency_p99={slo: pct(lat[slo], 99) for slo in SLO_CLASSES},
            slo_attainment={slo: attainment(slo) for slo in SLO_CLASSES},
            trace_counts=self.trace_counts(),
            prefill_count=self.prefill_count,
            **({"spec_accepted_k": (
                self._spec_committed / self._occupied_slot_ticks
                if self._occupied_slot_ticks else None)}
               if self._spec else {}),
            **({"prefix": self.prefix.stats()}
               if self.prefix is not None else {}),
        )

    def reset(self) -> None:
        """Drop queues/stats for a fresh measurement over the SAME arena
        (the jitted entry points and their compiled executables survive —
        bench_serve re-measures without re-paying compiles).  Refuses to
        reset a busy server."""
        assert not self.busy, "reset() on a busy server"
        self._flush_tick_agg()
        self.completed = []
        self.failed = []
        self.preemption_count = 0
        self._ticks = 0
        self._occupied_slot_ticks = 0
        self._decoded_tokens = 0
        self._spec_committed = 0
        self.prefill_count = 0
