"""graftwire: the fleet's RPC transport, with injectable failure.

The :class:`~.replica.Replica` contract (submit / collect / healthz /
drain / stop) was the transport boundary by design — DESIGN.md §17 kept
the router ignorant of everything behind ``Replica``'s surface.  This
module carries that contract across a process boundary on nothing but
the stdlib: length-prefixed JSON frames over a TCP socket, so a
``RemoteReplica`` (serve/remote.py) can drive a ``GenerationServer``
living in a subprocess while ``FleetRouter`` stays unchanged above the
seam.

**Frames.**  Every message is ``MAGIC (4B) | length (uint32 BE) | JSON
payload``.  Requests are ``{"id": seq, "method": name, "params": {...}}``;
responses ``{"id": seq, "ok": result}`` or ``{"id": seq, "err":
{"type": ExcName, "msg": str}}``.  numpy arrays ride as
``{"__nd__": [dtype, shape, flat-list]}`` — token ids and decoded codes
are small int32 vectors, so JSON beats inventing a binary layout the
next reader has to learn.

**Failure is typed, and the types are the taxonomy** the router's three
policies key off (see serve/remote.py for the mapping):

* :class:`WireUnavailable` — connect refused / no listener: the peer
  process is GONE (→ DEAD + migrate).
* :class:`WireTimeout` — the deadline expired with no response: maybe
  the request was lost, maybe only the response was — the *ambiguous*
  failure (→ retry, idempotent by request id, then migrate).
* :class:`WireReset` — the connection died mid-call (→ retry/migrate,
  same ambiguity as a timeout).
* :class:`WireProtocolError` — a torn or malformed frame: the bytes
  themselves can't be trusted, so retrying the same bytes is wrong
  (NEVER retried at this layer → surfaces as a health failure → drain).

**Every call** gets a deadline, bounded retries, and exponential
backoff with deterministic jitter — the constants are shared with
``tools/chip_babysitter.sh``'s healthz probe so the fleet has ONE
retry policy, not one per caller.

**Injection** (utils/faults.py): the ``rpc_send`` / ``rpc_recv`` sites
fire once per frame the CLIENT writes/reads — never on the server side,
so a test whose client and server share one in-process registry can aim
``rpc_send:drop=3`` at exactly the third outbound frame.  Actions:
``drop=N`` (the frame vanishes; a dropped recv is read-then-discarded,
i.e. the server executed — the idempotency drill), ``conn_reset=N``
(the socket is torn), ``truncate=N`` (half a frame → protocol error),
``delay_ms=V`` (per-hit latency).
"""
from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..obs import telemetry
from ..utils import faults
from ..utils import locks

MAGIC = b"GWR1"
MAX_FRAME_BYTES = 64 * 1024 * 1024  # a torn length field must not OOM us

# ONE retry policy for the fleet: the transport here and the babysitter's
# healthz probe (tools/chip_babysitter.sh) use the same constants
RETRY_ATTEMPTS = 3        # total tries per call
BACKOFF_BASE_S = 0.05     # first retry waits ~this
BACKOFF_CAP_S = 1.0       # exponential growth stops here
JITTER_FRAC = 0.25        # +/- fraction of the backoff, decorrelates herds


class WireError(RuntimeError):
    """Base of every transport-layer failure a :class:`WireClient` call
    can raise.  Subclasses ARE the failure taxonomy; callers map them to
    router policy, never parse messages."""


class WireUnavailable(WireError):
    """No listener: connect refused / name resolution / socket create
    failed.  The peer process is gone or never existed."""


class WireTimeout(WireError):
    """The call's deadline expired before a response arrived.  Ambiguous
    by nature: the request OR the response may have been lost — retries
    must be idempotent."""


class WireReset(WireError):
    """The connection died mid-call (ECONNRESET / broken pipe / EOF at a
    frame boundary).  Same ambiguity as a timeout."""


class WireProtocolError(WireError):
    """A malformed frame: bad magic, torn payload, unparseable JSON, or
    a response id that can't belong to this call.  Never retried at the
    transport layer — the same bytes would tear the same way."""


class WireRemoteError(WireError):
    """The peer executed the call and raised: ``etype`` carries the
    remote exception class name, ``msg`` its text.  Not a transport
    failure — the wire worked; the caller maps ``etype`` to a local
    exception (serve/remote.py keeps the table)."""

    def __init__(self, etype: str, msg: str):
        super().__init__(f"remote {etype}: {msg}")
        self.etype = etype
        self.msg = msg


# --- encoding ---------------------------------------------------------------


def _default(obj):
    if isinstance(obj, np.ndarray):
        return {"__nd__": [obj.dtype.str, list(obj.shape),
                           obj.ravel().tolist()]}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"not wire-encodable: {type(obj).__name__}")


def _object_hook(d):
    nd = d.get("__nd__")
    if nd is not None and len(d) == 1:
        dtype, shape, flat = nd
        return np.asarray(flat, dtype=np.dtype(dtype)).reshape(shape)
    return d


def encode(payload: Any) -> bytes:
    """One frame: MAGIC | uint32 length | JSON (numpy-aware)."""
    body = json.dumps(payload, default=_default,
                      separators=(",", ":")).encode("utf-8")
    return MAGIC + struct.pack(">I", len(body)) + body


def decode_body(body: bytes) -> Any:
    try:
        return json.loads(body.decode("utf-8"), object_hook=_object_hook)
    except (ValueError, UnicodeDecodeError) as e:
        raise WireProtocolError(f"unparseable frame body: {e}") from e


def _recv_exact(sock: socket.socket, n: int, *, mid_frame: bool) -> bytes:
    """Read exactly n bytes.  EOF at a frame boundary is a RESET (the
    peer closed between calls — retryable); EOF mid-frame is a torn
    frame (protocol error: bytes were lost, not a connection)."""
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout as e:
            raise WireTimeout("recv timed out mid-frame" if buf or mid_frame
                              else "recv timed out") from e
        except OSError as e:
            raise WireReset(f"recv failed: {e}") from e
        if not chunk:
            if buf or mid_frame:
                raise WireProtocolError(
                    f"torn frame: EOF after {len(buf)}/{n} bytes")
            raise WireReset("peer closed the connection")
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> Any:
    """Read one frame off ``sock`` (numpy-aware payload)."""
    header = _recv_exact(sock, 8, mid_frame=False)
    if header[:4] != MAGIC:
        raise WireProtocolError(f"bad magic {header[:4]!r}")
    (length,) = struct.unpack(">I", header[4:8])
    if length > MAX_FRAME_BYTES:
        raise WireProtocolError(f"frame length {length} exceeds cap")
    return decode_body(_recv_exact(sock, length, mid_frame=True))


# --- client -----------------------------------------------------------------


class WireClient:
    """One connection + one in-flight call at a time (serialized by a
    TracedLock — the pump/probe callers each own their own client when
    they must not contend).  Reconnects lazily; EVERY transport error
    closes the socket so a retry starts from a clean connection and a
    stale response can never be matched to a new call."""

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 5.0,
                 retry_attempts: int = RETRY_ATTEMPTS,
                 backoff_base_s: float = BACKOFF_BASE_S,
                 backoff_cap_s: float = BACKOFF_CAP_S,
                 jitter_frac: float = JITTER_FRAC,
                 jitter_seed: int = 0,
                 time_fn=time.monotonic):
        self.host = str(host)
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.retry_attempts = int(retry_attempts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.jitter_frac = float(jitter_frac)
        self._time = time_fn
        # deterministic jitter: tests pin the backoff schedule by seed
        self._rng = random.Random(jitter_seed)
        self._lock = locks.TracedLock("wire.client")
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._sleep_evt = threading.Event()  # interruptible backoff sleep
        self.calls = 0
        self.retries = 0

    # -- connection management --

    def _connect(self, deadline: float) -> socket.socket:
        if self._sock is not None:
            return self._sock
        try:
            sock = socket.create_connection(
                (self.host, self.port),
                timeout=max(0.001, deadline - self._time()))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except socket.timeout as e:
            raise WireTimeout(f"connect to {self.host}:{self.port} "
                              "timed out") from e
        except OSError as e:
            raise WireUnavailable(
                f"connect to {self.host}:{self.port} failed: {e}") from e
        self._sock = sock
        return sock

    def _teardown(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            self._sleep_evt.set()
            self._teardown()

    # -- fault injection (CLIENT side only) --

    def _fire_site(self, site: str) -> frozenset:
        delay_ms = faults.get_registry().config(site, "delay_ms")
        if delay_ms:
            # injected network latency: a plain bounded wait, per hit
            self._sleep_evt.wait(delay_ms / 1000.0)
        try:
            acts = faults.fire(site)
        except faults.InjectedFault as e:
            # fail_after/every on an rpc site: a generic transient
            # transport failure — same shape as a reset
            self._teardown()
            raise WireReset(f"injected transport fault at {site}") from e
        if "conn_reset" in acts:
            self._teardown()
            raise WireReset(f"injected conn_reset at {site}")
        return acts

    # -- the call --

    def call(self, method: str, params: Optional[dict] = None, *,
             deadline_s: Optional[float] = None) -> Any:
        """Invoke ``method`` on the peer; returns the decoded result.

        Bounded retry with exponential backoff + jitter on
        timeout/reset/unavailable (the ambiguous-or-transient class);
        protocol errors and remote errors surface immediately.  The
        whole attempt train shares ONE deadline."""
        deadline = self._time() + (self.timeout_s if deadline_s is None
                                   else float(deadline_s))
        last: Optional[WireError] = None
        with self._lock:
            self.calls += 1
            for attempt in range(1, self.retry_attempts + 1):
                try:
                    return self._call_once(method, params or {}, deadline)
                except (WireTimeout, WireReset, WireUnavailable) as e:
                    self._teardown()
                    last = e
                    telemetry.emit("wire", "retry", method=method,
                                   attempt=attempt, error=repr(e))
                    if attempt >= self.retry_attempts:
                        break
                    backoff = min(self.backoff_base_s * (2 ** (attempt - 1)),
                                  self.backoff_cap_s)
                    backoff *= 1.0 + self.jitter_frac * (
                        2.0 * self._rng.random() - 1.0)
                    if self._time() + backoff >= deadline:
                        break  # no budget left for another attempt
                    self.retries += 1
                    self._sleep_evt.wait(backoff)
                except WireProtocolError:
                    self._teardown()
                    raise
        assert last is not None
        raise last

    def _call_once(self, method: str, params: dict, deadline: float) -> Any:
        budget = deadline - self._time()
        if budget <= 0:
            raise WireTimeout(f"{method}: deadline exhausted before send")
        sock = self._connect(deadline)
        sock.settimeout(budget)
        self._seq += 1
        seq = self._seq
        frame = encode({"id": seq, "method": method, "params": params})

        acts = self._fire_site("rpc_send")
        if "truncate" in acts:
            # a torn outbound frame: the peer's reader discards it and
            # the connection is garbage — protocol error, not retried
            try:
                sock.sendall(frame[: max(1, len(frame) // 2)])
            except OSError:
                pass
            self._teardown()
            raise WireProtocolError(
                f"injected truncate at rpc_send ({method})")
        if "drop" not in acts:
            try:
                sock.sendall(frame)
            except socket.timeout as e:
                raise WireTimeout(f"{method}: send timed out") from e
            except OSError as e:
                raise WireReset(f"{method}: send failed: {e}") from e
        # a dropped send still WAITS: the caller learns via deadline,
        # exactly like a frame lost in the network

        while True:
            sock.settimeout(max(0.001, deadline - self._time()))
            resp = self._read_response(sock, method)
            rid = resp.get("id")
            if rid == seq:
                break
            if isinstance(rid, int) and rid < seq:
                continue  # stale response from an abandoned call: discard
            self._teardown()
            raise WireProtocolError(
                f"{method}: response id {rid!r} for request {seq}")
        if "err" in resp:
            err = resp["err"]
            raise WireRemoteError(str(err.get("type", "Exception")),
                                  str(err.get("msg", "")))
        return resp.get("ok")

    def _read_response(self, sock: socket.socket, method: str) -> dict:
        acts = self._fire_site("rpc_recv")
        if "truncate" in acts:
            # read-and-tear: pull the length header, then parse half the
            # body — the torn-frame read path, deterministically
            header = _recv_exact(sock, 8, mid_frame=False)
            if header[:4] != MAGIC:
                raise WireProtocolError(f"bad magic {header[:4]!r}")
            (length,) = struct.unpack(">I", header[4:8])
            body = _recv_exact(sock, length, mid_frame=True)
            self._teardown()
            return decode_body(body[: length // 2])  # raises
        resp = read_frame(sock)
        if not isinstance(resp, dict):
            raise WireProtocolError(f"{method}: non-object response")
        if "drop" in acts:
            # the response existed — the peer EXECUTED — but never
            # reached the caller: the ambiguous loss idempotency is for
            raise WireTimeout(
                f"{method}: response dropped (injected rpc_recv drop)")
        return resp


# --- server -----------------------------------------------------------------


class WireServer:
    """Frame server: one accept thread, one thread per connection, a
    dict of ``method -> callable(params) -> result``.  The server side
    NEVER fires fault sites — injection belongs to the caller's edge so
    shared-registry tests stay deterministic.  Handler exceptions are
    serialized as ``{type, msg}`` and the connection survives them; torn
    inbound frames close only that connection."""

    def __init__(self, handlers: Dict[str, Callable[[dict], Any]], *,
                 host: str = "127.0.0.1", port: int = 0):
        self.handlers = dict(handlers)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()[:2]
        self._stop_evt = threading.Event()
        self._lock = locks.TracedLock("wire.server")
        self._conns: list = []
        self._threads: list = []
        self._accept_thread: Optional[threading.Thread] = None
        self.requests = 0

    def start(self) -> "WireServer":
        assert self._accept_thread is None, "wire server already started"
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"wire-accept-{self.port}",
            daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutdown
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"wire-conn-{self.port}", daemon=True)
            with self._lock:
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop_evt.is_set():
                try:
                    req = read_frame(conn)
                except (WireReset, WireProtocolError, WireTimeout):
                    return  # torn/closed connection: drop it, serve on
                if not isinstance(req, dict) or "method" not in req:
                    return
                with self._lock:
                    self.requests += 1
                resp: dict = {"id": req.get("id")}
                handler = self.handlers.get(str(req["method"]))
                if handler is None:
                    resp["err"] = {"type": "NoSuchMethod",
                                   "msg": str(req["method"])}
                else:
                    try:
                        resp["ok"] = handler(req.get("params") or {})
                    # graftlint: disable=EXC001 (the RPC boundary: every handler exception is serialized typed to the caller, which maps it to router policy — swallowing here IS the delivery)
                    except Exception as e:
                        resp["err"] = {"type": type(e).__name__,
                                       "msg": str(e)}
                try:
                    conn.sendall(encode(resp))
                except OSError:
                    return  # peer gone mid-response
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop_evt.set()
        try:
            # close() alone does not wake a thread blocked in accept();
            # shutdown() makes it return EINVAL immediately.
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = list(self._conns), []
            threads, self._threads = list(self._threads), []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None
        for t in threads:
            t.join(timeout=2.0)
