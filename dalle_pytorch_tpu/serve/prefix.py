"""RadixPrefixCache: cross-request reuse of prompt prefill state.

``tile_prefill`` (models/dalle.py) rests on the property that a prompt's
K/V is continuation-independent — the prefill caches for a given token
sequence are a pure function of that sequence, whatever gets decoded
after it.  That same property makes prefill state *shareable across
requests*: two admissions with the same prompt can install copies of ONE
batch-1 prefill instead of running the transformer over the prompt
twice.  This module is the host-side index that makes the sharing safe:

* **A path-compressed radix tree over token tuples.**  Keys are the
  exact prompt token sequences; edges carry token *spans* (path
  compression keeps the node count proportional to the number of
  distinct prompts, not total tokens).  Lookup is exact-match: a hit
  returns the stored ``(first_logits, caches)`` device payload, which
  :meth:`SlotArena.admit` then rolls into a slot — admit does NOT donate
  its prefill arguments, so one payload can be installed into any number
  of slots.  (The tree — rather than a flat dict — is the structure the
  roadmap's shared-prefix *partial* reuse extends without re-keying:
  a future prefix hit is a walk that ends mid-edge.)
* **Refcount-guarded eviction.**  A payload acquired for a queued or
  running request is PINNED: ``acquire`` increments, the scheduler
  releases on retire/fail/preempt/stop, and eviction only ever considers
  entries at refcount zero (LRU order).  The cache may run over capacity
  while everything is pinned — correctness first, the capacity bound is
  advisory (tests/test_prefix.py pins the no-free-while-referenced
  property).
* **Observability in hardware units.**  Hits/misses and the prefill
  FLOPs a hit avoided (``utils.profiling.dalle_prefill_flops``)
  accumulate here; the scheduler exports them through ``stats()``,
  /metrics gauges and the telemetry stream obs_report aggregates.

Device memory: payloads are batch-1 caches — ``depth * 2 * heads *
seq_len * dim_head`` elements each (graftmem's ``serve-prefix`` row
budgets ``capacity`` of them).  The tree itself is host-side and tiny.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..utils import locks

Key = Tuple[int, ...]


class _Node:
    """One radix-tree node: ``edge`` is the token span from the parent
    (empty only at the root), ``children`` keys by each child's first
    edge token, ``entry`` is the terminal payload record (None for pure
    interior nodes)."""

    __slots__ = ("edge", "children", "entry")

    def __init__(self, edge: Key = ()):
        self.edge = tuple(edge)
        self.children: Dict[int, "_Node"] = {}
        self.entry: Optional["_Entry"] = None


class _Entry:
    __slots__ = ("key", "payload", "refcount", "flops", "last_used")

    def __init__(self, key: Key, payload, flops: float, stamp: int):
        self.key = key
        self.payload = payload
        self.refcount = 0
        self.flops = flops
        self.last_used = stamp


def _common_prefix_len(a: Key, b: Key) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class RadixPrefixCache:
    """Refcounted radix tree of prompt-token tuples -> batch-1 prefill
    payloads.  Thread-safe: every public method takes the internal
    ``prefix`` lock, so multiple replica drivers (or the router's retry
    path racing a driver) can acquire/insert/release concurrently without
    corrupting refcounts or the tree.

    ``capacity`` bounds the number of RESIDENT payloads; eviction is LRU
    over refcount-zero entries only, so the bound is exceeded while more
    than ``capacity`` payloads are pinned by live requests (the arena
    itself bounds how many can be running, so the overshoot is bounded
    too).  ``prefill_flops`` is the per-prompt forward cost a hit
    avoids; pass ``utils.profiling.dalle_prefill_flops(cfg)``."""

    def __init__(self, capacity: int = 32, *, prefill_flops: float = 0.0):
        assert capacity >= 1, "a zero-capacity prefix cache is just 'off'"
        self.capacity = capacity
        self.prefill_flops = float(prefill_flops)
        self._lock = locks.TracedLock("prefix")
        self._root = _Node()
        self._entries: Dict[Key, _Entry] = {}  # iteration/LRU index
        self._stamp = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.flops_saved = 0.0

    # --- radix-tree internals --------------------------------------------

    def _find(self, key: Key) -> Optional[_Node]:
        """Exact-match walk: the node whose root-path spells ``key``, or
        None (including walks that end mid-edge)."""
        node, i = self._root, 0
        while i < len(key):
            child = node.children.get(key[i])
            if child is None:
                return None
            edge = child.edge
            if tuple(key[i:i + len(edge)]) != edge:
                return None  # diverges inside (or beyond) the edge
            i += len(edge)
            node = child
        return node if i == len(key) else None

    def _insert_node(self, key: Key) -> _Node:
        """The node for ``key``, splitting edges as needed (standard
        path-compressed insert)."""
        node, i = self._root, 0
        while i < len(key):
            child = node.children.get(key[i])
            if child is None:
                leaf = _Node(key[i:])
                node.children[key[i]] = leaf
                return leaf
            p = _common_prefix_len(tuple(key[i:]), child.edge)
            if p == len(child.edge):
                node, i = child, i + p
                continue
            # split child's edge at p: node -> mid -> child
            mid = _Node(child.edge[:p])
            child.edge = child.edge[p:]
            mid.children[child.edge[0]] = child
            node.children[key[i]] = mid
            if i + p == len(key):
                return mid
            leaf = _Node(key[i + p:])
            mid.children[key[i + p]] = leaf
            return leaf
        return node

    def _remove(self, key: Key) -> None:
        """Drop ``key``'s entry and prune/re-merge the path (keeps the
        tree path-compressed as entries churn)."""
        path = [self._root]
        node, i = self._root, 0
        while i < len(key):
            child = node.children.get(key[i])
            assert child is not None, "removing a key that was never stored"
            path.append(child)
            i += len(child.edge)
            node = child
        node.entry = None
        # prune empty leaves upward, then merge single-child interior nodes
        for parent, n in zip(reversed(path[:-1]), reversed(path[1:])):
            if n.entry is None and not n.children:
                del parent.children[n.edge[0]]
            elif n.entry is None and len(n.children) == 1 and n is not self._root:
                (only,) = n.children.values()
                only.edge = n.edge + only.edge
                parent.children[n.edge[0]] = only
            else:
                break

    # --- public API (scheduler-facing) ------------------------------------

    def acquire(self, tokens) -> Optional[object]:
        """Exact-match lookup that PINS on hit: returns the payload with
        its refcount incremented (caller must :meth:`release` exactly
        once), or None on miss.  Hit/miss and FLOPs-saved counters
        update here."""
        key = tuple(int(t) for t in tokens)
        with self._lock:
            node = self._find(key)
            if node is None or node.entry is None:
                self.misses += 1
                return None
            entry = node.entry
            entry.refcount += 1
            self._stamp += 1
            entry.last_used = self._stamp
            self.hits += 1
            self.flops_saved += entry.flops
            return entry.payload

    def insert(self, tokens, payload) -> object:
        """Store a freshly-computed prefill payload and pin it for the
        inserting request (refcount starts at 1 — the caller releases it
        like an acquire).  Runs LRU eviction of unpinned entries if over
        capacity.  Idempotent on key collision: keeps the resident
        payload and pins that instead (two racing misses on one prompt
        must not hold divergent device copies)."""
        key = tuple(int(t) for t in tokens)
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                existing.refcount += 1
                self._stamp += 1
                existing.last_used = self._stamp
                return existing.payload
            self._stamp += 1
            entry = _Entry(key, payload, self.prefill_flops, self._stamp)
            entry.refcount = 1
            self._insert_node(key).entry = entry
            self._entries[key] = entry
            self._evict_to_capacity_locked()
            return entry.payload

    def release(self, tokens) -> None:
        """Unpin one reference (retire/fail/preempt/stop all funnel
        here).  The payload stays resident for future hits until LRU
        eviction claims it."""
        key = tuple(int(t) for t in tokens)
        with self._lock:
            entry = self._entries.get(key)
            assert entry is not None, "release of an untracked prefix"
            assert entry.refcount > 0, "refcount underflow — double release"
            entry.refcount -= 1
            self._evict_to_capacity_locked()

    def _evict_to_capacity_locked(self) -> None:
        while len(self._entries) > self.capacity:
            victims = [e for e in self._entries.values() if e.refcount == 0]
            if not victims:
                return  # everything pinned: over-capacity is allowed
            victim = min(victims, key=lambda e: e.last_used)
            self._remove(victim.key)
            del self._entries[victim.key]
            self.evictions += 1

    def stats(self) -> dict:
        with self._lock:
            looked = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "pinned": sum(1 for e in self._entries.values()
                              if e.refcount > 0),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / looked) if looked else 0.0,
                "evictions": self.evictions,
                "prefill_flops_saved": self.flops_saved,
            }
