"""SlotArena: the fixed-shape KV-cache arena behind continuous batching.

The static-batch sampler (``models/dalle.py::decode_codes``) turns one
batch of one prompt into image codes at full device efficiency — but a
*service* sees requests arriving at arbitrary times, and re-batching them
into aligned cohorts leaves decode slots idle while stragglers finish (the
head-of-line blocking the Orca iteration-level-scheduling paper measures).
This module is the device half of the fix:

* **One arena, N slots, every shape static.**  The KV caches live in
  per-layer arrays ``[num_slots, heads, seq_len, dim_head]`` allocated
  once.  A request occupies one slot; its per-slot decode position is a
  *traced* ``int32``, so slots at different depths of their decode share
  one compiled program.
* **Admission is a ``dynamic_update_slice``, never a retrace.**  A new
  request is prefilled at batch 1 (one compiled prefill shape), then its
  caches are written into a free slot by the jitted :meth:`SlotArena.admit`
  — the slot id is traced, so admitting into slot 0 and slot 17 is the
  same executable.  Retiring a finished request is pure host bookkeeping
  (the slot is marked free; its stale cache bytes are overwritten by the
  next admit and are unreachable meanwhile — decode attention masks keys
  beyond the slot's position).
* **One jitted tick decodes every occupied slot.**  :meth:`SlotArena.tick`
  runs the batched ``DALLE.decode_step`` with a per-slot position vector
  and a per-slot active mask: occupied slots advance one token, free
  slots burn a masked lane (fixed shapes are the point — the mask changes
  per tick as requests come and go, but it is a *traced* input, so
  occupancy changes never recompile).  graftspmd S3 gates exactly this
  (``tools/spmd_check.py`` serve-tick harness): N simulated admit/retire
  cycles across differing occupancies must leave ``_cache_size == 1`` on
  every jitted entry point.
* **Phase-aligned (circular) slot caches.**  Slots sit at different
  depths, but a per-slot cache-write position would lower to an XLA
  scatter — which copies the whole arena on backends that don't alias it
  (measured ~2x the whole decode step on CPU).  Instead each slot's cache
  is stored ROTATED by ``(clock - index) mod seq_len`` (established once
  at admit by rolling the prefilled caches), so at every tick ALL slots
  write their new k/v at the same physical column — the arena clock mod
  seq_len — one plain in-place ``dynamic_update_slice``.  Attention masks
  translate physical -> logical per slot (``ops/attention.py::
  MultiHeadAttention._decode_step_aligned``), which also hides the
  previous resident's stale keys.

Sampling reuses ``models.dalle.sample_image_code`` — the serve path and
``decode_codes`` share one sampler, so semantics cannot drift; temperature
rides per-slot as a traced array (a per-request knob), while
``filter_thres``/``top_p`` are server-static (they derive static shapes).

The host-side queueing/SLO policy lives in ``serve/scheduler.py``; this
module knows nothing about requests, only slots.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ..models.dalle import (DALLE, prefill_codes, quantize_decode_weights,
                            sample_image_code)
from ..obs import prof
from ..ops.quant import split_cache


@dataclasses.dataclass(frozen=True)
class ArenaGeometry:
    """Static facts of one arena build (host-side mirrors of the traced
    state the scheduler needs for progress accounting)."""

    num_slots: int
    n_pre: int            # absolute input position of the first decode step
    image_seq_len: int    # codes produced per request
    seq_len: int


class SlotArena:
    """Device state + the three jitted entry points of the serving engine.

    ``variables`` is the flax variables dict (``{"params": ...}``) the
    generation primitives take.  All three entry points donate the arena
    state, so the caches update in place; callers must always thread the
    *returned* state (the donated input buffers are dead)."""

    def __init__(self, dalle: DALLE, variables, num_slots: int, *,
                 filter_thres: float = 0.9,
                 top_p: Optional[float] = None):
        cfg = dalle.cfg
        self.dalle = dalle
        self.variables = variables
        self.geometry = ArenaGeometry(
            num_slots=num_slots, n_pre=cfg.text_seq_len + 1,
            image_seq_len=cfg.image_seq_len, seq_len=cfg.seq_len)
        # cache STORAGE layout matches what prefill returns (models/dalle.py
        # quantizes under kv_cache_int8, casts to bf16 under kv_cache_bf16)
        # — admit's astype is then a no-op and the arena carries the same
        # byte-cut the static sampler measured.  Int8 arenas ride PER-SLOT
        # per-head f32 scale planes [S, heads, 1, 1] next to the int8
        # values; scale-plane init is ones, not zeros — a never-admitted
        # slot's masked lane still divides by its scale in the tick's
        # saturating re-quantize, and 0/0 would poison it with NaNs.
        self._cache_dtype = (jnp.int8 if cfg.kv_cache_int8
                             else jnp.bfloat16 if cfg.kv_cache_bf16
                             else cfg.dtype)
        S = num_slots
        cache_shape = (S, cfg.heads, cfg.seq_len, cfg.dim_head)

        def fresh_entry():
            values = jnp.zeros(cache_shape, self._cache_dtype)
            if not cfg.kv_cache_int8:
                return values
            return (values, jnp.ones((S, cfg.heads, 1, 1), jnp.float32))

        # weights_int8: the per-session one-shot quantization — computed
        # here, once per arena, and passed to every tick as an argument
        # (the tick's compiled program then consumes ONLY the int8 copies;
        # jit prunes the unused f32 kernels from its argument list)
        self._qweights = (jax.jit(
            lambda v: quantize_decode_weights(v, cfg))(variables)
            if cfg.weights_int8 else None)

        def fresh_state():
            return dict(
                caches=[(fresh_entry(), fresh_entry())
                        for _ in range(cfg.depth)],
                code=jnp.zeros((S,), jnp.int32),
                index=jnp.zeros((S,), jnp.int32),
                pos=jnp.zeros((S,), jnp.int32),
                # per-slot PRE-SPLIT key stream, one key per decoded code
                # (decode_codes splits all its scan keys up front for the
                # same reason: a threefry split inside the hot loop costs
                # more than the toy-model decode step on CPU).  admit pays
                # one vectorized split; the tick only gathers.
                keys=jnp.zeros((S, cfg.image_seq_len, 2), jnp.uint32),
                # temp divides logits — a zero in a never-admitted slot
                # would poison that (masked) lane's sampler with inf/nan
                temp=jnp.ones((S,), jnp.float32),
                out=jnp.zeros((S, cfg.image_seq_len), jnp.int32),
                # spec_decode: per-slot cache rotation, FROZEN at admit.
                # The greedy tick derives the shared write column from the
                # arena clock (all slots advance together); a speculative
                # tick advances each slot by a per-slot accepted length m,
                # so the clock identity breaks — each slot keeps the
                # rotation its prefill was installed with and decode_span
                # scatters at per-row physical columns instead.
                **({"rot": jnp.zeros((S,), jnp.int32)}
                   if cfg.spec_decode else {}),
            )

        self.state = jax.jit(fresh_state)()
        n_pre = self.geometry.n_pre
        k_vocab = cfg.total_tokens

        def sample_one(logits, key, temp):
            # [V] logits, [2] key, scalar temp -> scalar code; vmapped over
            # the slot axis so each slot draws from its own request key
            return sample_image_code(
                logits, key, k_vocab=k_vocab, filter_thres=filter_thres,
                temperature=temp, top_p=top_p)

        def prefill(variables, text):
            return prefill_codes(dalle, variables, text)

        def admit(state, slot, first_logits, caches1, key, temp, write_pos):
            """Install a batch-1 prefill into (traced) ``slot``: one
            dynamic_update_slice per cache array, plus the request's first
            sampled code — mirrors decode_codes' pre-scan sampling.

            ``write_pos`` is the physical column the NEXT tick writes (the
            arena clock mod seq_len): the prefill caches are rolled so the
            slot's logical position ``n_pre`` lands exactly there —
            establishing the rotation every later tick relies on to keep
            its cache write one shared-column dynamic_update_slice."""
            rot = jnp.remainder(write_pos - jnp.int32(n_pre),
                                jnp.int32(self.geometry.seq_len))

            def install(arena_entry, new_entry):
                """Roll the prefilled values into the slot's rotation and
                write them (one DUS); int8 entries also carry the slot's
                per-head scale plane across — scales are write-position-
                invariant, so only the values roll."""
                vals, scale = split_cache(arena_entry)
                new_vals, new_scale = split_cache(new_entry)
                vals = jax.lax.dynamic_update_slice(
                    vals, jnp.roll(new_vals.astype(vals.dtype), rot, axis=2),
                    (slot, 0, 0, 0))
                if scale is None:
                    return vals
                return (vals, jax.lax.dynamic_update_slice(
                    scale, new_scale, (slot, 0, 0, 0)))

            caches = [(install(ak, k1), install(av, v1))
                      for (ak, av), (k1, v1) in zip(state["caches"], caches1)]
            ks = jax.random.split(key, self.geometry.image_seq_len)
            code0 = sample_one(first_logits[0], ks[0], temp)

            def set1(arr, val, dtype=None):
                return jax.lax.dynamic_update_slice(
                    arr, jnp.asarray(val, dtype or arr.dtype)[None], (slot,))

            out_row = jnp.zeros((self.geometry.image_seq_len,), jnp.int32
                                ).at[0].set(code0)
            return dict(
                caches=caches,
                code=set1(state["code"], code0),
                index=set1(state["index"], jnp.int32(n_pre)),
                pos=set1(state["pos"], jnp.int32(1)),
                keys=jax.lax.dynamic_update_slice(
                    state["keys"], ks[None], (slot, 0, 0)),
                temp=set1(state["temp"], temp),
                out=jax.lax.dynamic_update_slice(
                    state["out"], out_row[None], (slot, 0)),
                **({"rot": set1(state["rot"], rot)}
                   if cfg.spec_decode else {}),
            )

        def tick(variables, state, active, write_pos, qweights):
            """One decode step over every slot (phase-aligned batched
            ``DALLE.decode_step``: per-slot logical ``index`` vector, one
            shared physical write column).  ``active`` [S] bool masks
            which slots advance; masked lanes still compute (fixed shape)
            but their code/pos/index/out are held, and their junk cache
            write lands in the shared column — overwritten by the next
            admit, unreachable before it (the aligned mask only reaches
            logical positions a resident actually wrote).  ``qweights``
            (weights_int8) rides as a real argument so the executable's
            weight stream is the int8 copies, never a baked-in constant."""
            with prof.scope("serve-tick"):
                logits, caches = dalle.apply(
                    variables, state["code"], state["caches"], state["index"],
                    None, write_pos, qweights, method=DALLE.decode_step)
                # per-slot key for THIS position, gathered from the pre-split
                # stream (no threefry in the tick)
                sub = jax.vmap(
                    lambda ks, p: jax.lax.dynamic_slice(
                        ks, (p, 0), (1, 2))[0])(state["keys"], state["pos"])
                sampled = jax.vmap(sample_one)(logits, sub, state["temp"])

                adv = active.astype(jnp.int32)
                written = jax.vmap(
                    lambda row, p, val: jax.lax.dynamic_update_slice(
                        row, val[None], (p,)))(state["out"], state["pos"],
                                               sampled)
                return dict(
                    caches=caches,
                    code=jnp.where(active, sampled, state["code"]),
                    index=state["index"] + adv,
                    pos=state["pos"] + adv,
                    keys=state["keys"],
                    temp=state["temp"],
                    out=jnp.where(active[:, None], written, state["out"]),
                    **({"rot": state["rot"]} if cfg.spec_decode else {}),
                )

        K = cfg.spec_k
        L = self.geometry.image_seq_len

        def tick_spec(variables, state, active, qweights):
            """One SPECULATIVE decode tick over every slot: draft ``K-1``
            tokens through the first ``spec_draft_depth`` blocks, score
            all ``K`` span positions with ONE full-depth
            ``DALLE.decode_span`` pass, commit the accepted prefix plus
            the verifier's correction.  Returns ``(state, m)`` where
            ``m`` [S] int32 is each slot's committed-token count this
            tick (1 <= m <= K for active slots, 0 for masked lanes) —
            the scheduler's variable-rate progress accounting input.

            Bit-equality with the greedy tick holds by construction:
            lane ``j``'s verify read is the greedy tick's exact
            attention program (``_aligned_read`` over the folded batch),
            lane keys are the same pre-split per-position stream the
            greedy tick gathers, and a draft for out position ``p`` is
            sampled with position ``p``'s key — so an accepted draft IS
            the token greedy would have sampled.  Rejected lanes leave
            junk k/v beyond ``index + m``; those rows are causally
            unreadable this tick and the next span (``m >= 1``) rewrites
            them before any read."""
            with prof.scope("serve-tick"):
                pos = state["pos"]          # [S] decoded-token count
                index = state["index"]      # [S] input position of `code`
                rot = state["rot"]
                remaining = jnp.int32(L) - pos
                # per-slot keys for out positions pos..pos+K-1 (clipped —
                # lanes past `remaining` are masked, their key is unused)
                kspan = jax.vmap(
                    lambda ks, p: jnp.take(
                        ks, jnp.clip(p + jnp.arange(K), 0, L - 1),
                        axis=0))(state["keys"], pos)          # [S, K, 2]
                caches = state["caches"]
                lanes = jnp.arange(K)[None, :]                # [1, K]
                d = state["code"]
                drafts = []
                with prof.scope("spec-draft"):
                    for j in range(1, K):
                        qp = (index + (j - 1))[:, None]
                        dvalid = (active & (j - 1 < remaining))[:, None]
                        dlogits, caches = dalle.apply(
                            variables, d[:, None], caches, qp, rot,
                            dvalid, cfg.spec_draft_depth, qweights,
                            method=DALLE.decode_span)
                        # draft for out position pos+j-1: SAME key the
                        # verifier's lane j-1 commit uses, so a correct
                        # shallow guess is accepted bit-for-bit
                        d = jax.vmap(sample_one)(
                            dlogits[:, 0], kspan[:, j - 1], state["temp"])
                        drafts.append(d)
                with prof.scope("spec-verify"):
                    t = jnp.stack([state["code"]] + drafts, axis=1)
                    qpos = index[:, None] + lanes              # [S, K]
                    vvalid = active[:, None] & (lanes < remaining[:, None])
                    vlogits, caches = dalle.apply(
                        variables, t, caches, qpos, rot, vvalid, None,
                        qweights, method=DALLE.decode_span)
                    cand = jax.vmap(jax.vmap(
                        sample_one, in_axes=(0, 0, None)))(
                            vlogits, kspan, state["temp"])     # [S, K]
                    if cfg.spec_force_reject:
                        matches = jnp.zeros_like(pos)
                    else:
                        matches = jnp.sum(jnp.cumprod(
                            (t[:, 1:] == cand[:, :-1]).astype(jnp.int32),
                            axis=1), axis=1)
                    m = jnp.where(
                        active,
                        jnp.minimum(matches + 1, jnp.maximum(remaining, 1)),
                        0)
                    last = jnp.take_along_axis(
                        cand, jnp.clip(m - 1, 0, K - 1)[:, None],
                        axis=1)[:, 0]

                    def write_row(row, p, cand_row, mm):
                        idxs = jnp.where(jnp.arange(K) < mm,
                                         p + jnp.arange(K), L)
                        return row.at[idxs].set(cand_row, mode="drop")

                    return dict(
                        caches=caches,
                        code=jnp.where(active, last, state["code"]),
                        index=index + m,
                        pos=pos + m,
                        keys=state["keys"],
                        temp=state["temp"],
                        out=jax.vmap(write_row)(
                            state["out"], pos, cand, m),
                        rot=rot,
                    ), m

        self._prefill = jax.jit(prefill)
        self._admit = jax.jit(admit, donate_argnums=(0,))
        self._tick = jax.jit(tick, donate_argnums=(1,))
        self._tick_spec = (jax.jit(tick_spec, donate_argnums=(1,))
                           if cfg.spec_decode else None)

    # --- public API (scheduler-facing) ------------------------------------

    def prefill(self, text):
        """Batch-1 prompt prefill: ``text`` [1, text_seq_len] int32 ->
        (first_logits, caches) device state for :meth:`admit`.  One
        compiled shape for every request."""
        return self._prefill(self.variables, text)

    def admit(self, slot: int, first_logits, caches1, key, temperature,
              clock: int):
        """Write a prefilled request into ``slot`` (traced — no retrace
        across slots) and sample its first code.  ``clock`` is the arena
        tick counter the NEXT tick will run at — it fixes the slot's
        cache rotation.  Mutates ``self.state`` (donated)."""
        self.state = self._admit(
            self.state, jnp.int32(slot), first_logits, caches1,
            jnp.asarray(key, jnp.uint32),
            jnp.float32(temperature),
            jnp.int32(clock % self.geometry.seq_len))

    def tick(self, active_mask, clock: int):
        """Advance every slot where ``active_mask`` [num_slots] bool is
        set by one decoded token; ``clock`` is the arena tick counter
        (all running slots write physical column ``clock % seq_len``).
        Mutates ``self.state`` (donated)."""
        self.state = self._tick(self.variables, self.state,
                                jnp.asarray(active_mask),
                                jnp.int32(clock % self.geometry.seq_len),
                                self._qweights)

    def tick_spec(self, active_mask):
        """Advance every active slot by its ACCEPTED speculative span
        (1..spec_k tokens) in one jitted call; returns the per-slot
        committed-token counts [num_slots] as host numpy.  No clock —
        each slot writes at its admit-frozen rotation.  Mutates
        ``self.state`` (donated)."""
        assert self._tick_spec is not None, (
            "tick_spec requires DALLEConfig.spec_decode=True")
        self.state, m = self._tick_spec(self.variables, self.state,
                                        jnp.asarray(active_mask),
                                        self._qweights)
        return jax.device_get(m)

    def fetch_codes(self, slot: int):
        """Host numpy of one slot's decoded codes [image_seq_len] — the
        retirement read.  Blocks until every dispatched tick touching the
        slot has landed."""
        return jax.device_get(self.state["out"][slot])

    def trace_counts(self) -> dict:
        """Executable-cache population per jitted entry point — the
        no-recompile sentinel the S3 serve gate and tests assert on.  A
        healthy server holds every count at 1 forever, whatever the
        admit/retire pattern."""
        decode = (("tick_spec", self._tick_spec)
                  if self._tick_spec is not None else ("tick", self._tick))
        return {name: int(fn._cache_size())
                for name, fn in (("prefill", self._prefill),
                                 ("admit", self._admit), decode)}
