"""graftwire remote replicas: a GenerationServer in another process.

Two halves of one seam (DESIGN.md §21):

* :class:`ReplicaServer` — runs NEXT TO a :class:`~.replica.Replica`
  (in the subprocess, or in-thread for deterministic tests) and exposes
  its contract over :mod:`~.wire`: ``submit`` / ``collect`` /
  ``healthz`` / ``drain`` / ``stop`` / ``ping``.  Results are delivered
  **at-least-once with acks** (a result stays buffered until the client
  acknowledges it in a later ``collect``), and submissions are
  **idempotent by wid** — a work id the client derives from the pinned
  request key — so a retry after an ambiguous timeout can never
  double-execute: the duplicate submit attaches to the execution
  already in flight.
* :class:`RemoteReplica` — the client half, presenting the exact
  ``Replica`` surface :class:`~.router.FleetRouter` already consumes
  (``state`` / ``alive()`` / ``beat_age()`` / ``healthz()`` /
  ``begin_drain`` / ``finish_drain`` / ``halt`` / ``server.submit`` /
  ``server.backlog()``), so the router needs NO remote-aware code.

The transport failure taxonomy maps onto the router's three existing
policies:

======================  =====================================  ========
wire failure            RemoteReplica surface                  policy
======================  =====================================  ========
connect refused         ``alive()`` → False                    2: DEAD + migrate
deadline / reset        ``submit`` raises :class:`ReplicaDown` 1: retry → migrate
torn frame (protocol)   ``healthz()`` → ``ok: False`` sticky   3: drain
remote heartbeat stale  ``healthz()`` → ``ok: False``          3: drain
======================  =====================================  ========

The subprocess entry point (``python -m dalle_pytorch_tpu.serve.remote``)
builds the CI-scale toy model, owns its OWN graftscope lane
(``--telemetry-dir``, with its own boot nonce and clock beacons — the
merged fleet report aligns it like any other host) and its own
``/metrics`` port, and announces readiness by atomically writing a JSON
ready-file (``{port, metrics_port, pid}``).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import telemetry
from ..utils import faults
from ..utils import locks
from . import wire
from .replica import DEAD, DRAINING, JOINING, SERVING, Replica, ReplicaDown
from .scheduler import LATENCY, SLO_CLASSES, THROUGHPUT, ServerStopped

REPO_ROOT = Path(__file__).resolve().parents[2]

_EMPTY_BACKLOG = {"queued": {slo: 0 for slo in SLO_CLASSES},
                  "queued_total": 0, "running": 0}

# scale_signals before first contact: unknown headroom, zero ledger —
# the autoscaler treats an all-default row as "no information yet"
_EMPTY_SIGNALS = {"queued": {slo: 0 for slo in SLO_CLASSES}, "running": 0,
                  "num_slots": 0, "headroom_bytes": None,
                  "predicted_bytes_per_token": 0, "ledger_fingerprint": "",
                  "spec": False, "spec_capable": False}


class SpawnFailed(RuntimeError):
    """:func:`spawn_replica`'s ready-file handshake failed: the child
    exited before announcing readiness (``rc`` set) or never wrote the
    ready file inside the timeout (``rc`` None).  Either way the child
    has been killed AND reaped before this raises — a failed spawn never
    leaks an orphan process.  graftscale's spawn budget counts these."""

    def __init__(self, msg: str, *, name: str = "",
                 rc: Optional[int] = None):
        super().__init__(msg)
        self.name = name
        self.rc = rc

# remote exception-name -> local type: how a collected error re-raises
# on the caller's side of the wire.  Transient types keep their transient
# meaning (the router retries them); anything unknown is terminal.
_TRANSIENT_ERRORS = {
    "ReplicaDown": ReplicaDown,
    "ServerStopped": ServerStopped,
    "InjectedFault": faults.InjectedFault,
}


def _map_remote_error(err: dict) -> BaseException:
    etype = str(err.get("type", "Exception"))
    msg = str(err.get("msg", ""))
    cls = _TRANSIENT_ERRORS.get(etype)
    if cls is not None:
        return cls(f"remote {etype}: {msg}")
    return RuntimeError(f"remote {etype}: {msg}")


# --- server half ------------------------------------------------------------


class ReplicaServer:
    """Wire front end over a local :class:`Replica`.

    Exactly-once bookkeeping: ``_pending`` holds executions in flight,
    ``_done`` holds results awaiting an ack, ``_delivered_ok`` pins the
    wids whose SUCCESS was acknowledged (a duplicate submit of one of
    those is a pure no-op).  An acknowledged *error* forgets its wid
    entirely — the router retrying the same replica after a transient
    failure must re-execute, not replay the stale error."""

    def __init__(self, replica: Replica, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.replica = replica
        self._lock = locks.TracedLock("remote.server")
        self._pending: Dict[str, object] = {}
        self._done: Dict[str, dict] = {}
        self._delivered_ok: set = set()
        self.dedup_hits = 0
        self.submits = 0
        self.shutdown_evt = threading.Event()
        self._wire = wire.WireServer({
            "submit": self._h_submit,
            "collect": self._h_collect,
            "healthz": self._h_healthz,
            "drain": self._h_drain,
            "stop": self._h_stop,
            "ping": self._h_ping,
            "configure": self._h_configure,
        }, host=host, port=port)
        self.port = self._wire.port

    def start(self) -> "ReplicaServer":
        self._wire.start()
        return self

    def close(self) -> None:
        self._wire.close()

    def wait_shutdown(self, timeout_s: Optional[float] = None) -> bool:
        return self.shutdown_evt.wait(timeout_s)

    # -- handlers (run on wire connection threads) --

    def _h_submit(self, params: dict) -> dict:
        wid = str(params["wid"])
        with self._lock:
            if (wid in self._pending or wid in self._done
                    or wid in self._delivered_ok):
                # the idempotency contract: a duplicate submit (transport
                # retry, or a router re-dispatch after an ambiguous
                # timeout) attaches to the execution already in flight
                self.dedup_hits += 1
                return {"accepted": True, "dup": True}
        handle = self.replica.server.submit(
            np.asarray(params["text"], np.int32),
            slo=str(params.get("slo", THROUGHPUT)),
            temperature=float(params.get("temperature", 1.0)),
            key=np.asarray(params["key"], np.uint32))
        with self._lock:
            self.submits += 1
            self._pending[wid] = handle
        handle.future.add_done_callback(
            lambda f, wid=wid: self._on_done(wid, f))
        return {"accepted": True, "dup": False}

    def _on_done(self, wid: str, f: Future) -> None:
        exc = f.exception()
        if exc is None:
            entry = {"wid": wid, "ok": np.asarray(f.result(0))}
        else:
            entry = {"wid": wid, "err": {"type": type(exc).__name__,
                                         "msg": str(exc)}}
        with self._lock:
            self._pending.pop(wid, None)
            self._done[wid] = entry

    def _heartbeat(self) -> dict:
        r = self.replica
        return {"state": r.state, "beat_age_s": round(r.beat_age(), 4),
                "ticks": r.ticks, "work_ticks": r.work_ticks,
                "busy": bool(r.server.busy),
                "backlog": r.server.backlog(),
                # graftscale's observation row rides every collect, so
                # the client-side cache is at most one pump tick stale
                "signals": r.server.scale_signals()}

    def _h_collect(self, params: dict) -> dict:
        with self._lock:
            for wid in params.get("ack") or ():
                entry = self._done.pop(str(wid), None)
                if entry is not None and "ok" in entry:
                    self._delivered_ok.add(str(wid))
            results = list(self._done.values())
        return {"results": results, **self._heartbeat()}

    def _h_healthz(self, params: dict) -> dict:
        return self.replica.healthz()

    def _h_drain(self, params: dict) -> dict:
        evicted = self.replica.begin_drain(
            reason=str(params.get("reason", "remote drain")))
        return {"draining": True, "evicted": len(evicted)}

    def _h_stop(self, params: dict) -> dict:
        mode = str(params.get("mode", "halt"))
        if mode == "drain":
            left = self.replica.finish_drain()
        else:
            left = self.replica.halt(ReplicaDown(
                f"replica {self.replica.name}: remote halt"))
        if params.get("final"):
            self.shutdown_evt.set()
        return {"stopped": True, "mode": mode, "left": len(left)}

    def _h_ping(self, params: dict) -> dict:
        return {"ok": True, "pid": os.getpid(),
                "replica": self.replica.name}

    def _h_configure(self, params: dict) -> dict:
        """Runtime knobs the autoscaler turns fleet-wide (brownout rung
        1: spec decode off/on).  Returns the state actually in force —
        a spec-incapable plan answers ``spec: False`` to an enable."""
        out: dict = {"ok": True}
        if "spec" in params:
            out["spec"] = bool(
                self.replica.server.set_spec(bool(params["spec"])))
        return out


# --- client half ------------------------------------------------------------


@dataclasses.dataclass
class RemoteHandle:
    """Client-side stand-in for a remote ``ServeHandle``: the local
    future the router wires its done-callback to."""

    request_id: int
    wid: str
    slo: str
    future: Future


class _RemoteServerFacade:
    """The slice of ``GenerationServer``'s surface the router touches
    (``submit`` / ``backlog()`` / ``busy``), backed by RPC + the cached
    heartbeat the collect pump refreshes."""

    def __init__(self, remote: "RemoteReplica"):
        self._r = remote

    def submit(self, text, *, slo: str = THROUGHPUT,
               temperature: float = 1.0, key=None):
        return self._r._submit(text, slo=slo, temperature=temperature,
                               key=key)

    def backlog(self) -> dict:
        return self._r._cached_backlog()

    def scale_signals(self) -> dict:
        return self._r._cached_signals()

    def set_spec(self, enabled: bool) -> bool:
        return self._r._configure_spec(enabled)

    @property
    def busy(self) -> bool:
        return self._r._busy()


class RemoteReplica:
    """The router-facing half: ``Replica``'s surface over the wire.

    A **pump thread** (the ``_thread`` the router's liveness check sees)
    polls ``collect`` — harvesting results, acking deliveries, and
    refreshing the cached remote heartbeat.  ``last_beat`` is the last
    *successful transport contact*: a SIGKILLed or wedged peer stops
    refreshing it and policy 2 (heartbeat staleness → DEAD + migrate)
    fires exactly as it does for an in-process corpse."""

    def __init__(self, name: str, host: str, port: int, *,
                 num_slots: int = 2, proc: Optional[subprocess.Popen] = None,
                 call_timeout_s: float = 5.0,
                 submit_timeout_s: Optional[float] = None,
                 poll_interval_s: float = 0.02,
                 remote_stale_s: float = 5.0,
                 jitter_seed: int = 0, time_fn=time.monotonic):
        self.name = str(name)
        self.num_slots = int(num_slots)
        self.proc = proc
        self.call_timeout_s = float(call_timeout_s)
        self.submit_timeout_s = float(call_timeout_s if submit_timeout_s
                                      is None else submit_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.remote_stale_s = float(remote_stale_s)
        self._time = time_fn
        self._client = wire.WireClient(host, port, timeout_s=call_timeout_s,
                                       jitter_seed=jitter_seed)
        # probes ride their own connection: a healthz must not queue
        # behind a slow collect on the pump's client
        self._probe = wire.WireClient(host, port, timeout_s=call_timeout_s,
                                      jitter_seed=jitter_seed + 1)
        self._lock = locks.TracedLock("remote.replica")
        self._pending: Dict[str, RemoteHandle] = {}
        self._to_ack: set = set()
        self._remote: dict = {"state": JOINING, "beat_age_s": 0.0,
                              "busy": False, "backlog": dict(_EMPTY_BACKLOG),
                              "signals": dict(_EMPTY_SIGNALS),
                              "ticks": 0, "work_ticks": 0}
        self._state_hint: Optional[str] = None  # DRAINING/DEAD overlay
        self._protocol_errors = 0
        self._dead = False
        self._dead_reason = ""
        self.last_beat = self._time()
        self._next_rid = 0
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.server = _RemoteServerFacade(self)

    # -- lifecycle surface (what FleetRouter consumes) --

    @property
    def state(self) -> str:
        with self._lock:
            if self._state_hint is not None:
                return self._state_hint
            return self._remote["state"]

    @property
    def ticks(self) -> int:
        with self._lock:
            return int(self._remote["ticks"])

    @property
    def work_ticks(self) -> int:
        with self._lock:
            return int(self._remote["work_ticks"])

    def start(self) -> "RemoteReplica":
        assert self._thread is None, f"remote {self.name} already started"
        self._thread = threading.Thread(
            target=self._pump, name=f"remote-pump-{self.name}", daemon=True)
        self._thread.start()
        return self

    def alive(self) -> bool:
        return (not self._dead and self._thread is not None
                and self._thread.is_alive())

    def beat_age(self) -> float:
        return self._time() - self.last_beat

    def healthz(self) -> dict:
        """Active probe, mapped to policy 3 (drain): transport probe
        failures, any observed protocol error (sticky — torn frames mean
        the wire itself can't be trusted), and a STALE REMOTE heartbeat
        (the peer's driver wedged while its RPC plane still answers) all
        read as unhealthy."""
        if self._dead:
            return {"ok": False, "replica": self.name,
                    "error": f"transport dead: {self._dead_reason}"}
        try:
            hz = self._probe.call("healthz", {},
                                  deadline_s=self.call_timeout_s)
        except wire.WireProtocolError as e:
            self._note_protocol_error(e)
            return {"ok": False, "replica": self.name,
                    "error": f"protocol error: {e}"}
        except wire.WireUnavailable as e:
            self._mark_dead(f"healthz connect refused: {e}")
            return {"ok": False, "replica": self.name, "error": repr(e)}
        except wire.WireError as e:
            return {"ok": False, "replica": self.name, "error": repr(e)}
        self.last_beat = self._time()
        with self._lock:
            protocol_errors = self._protocol_errors
        if protocol_errors:
            return {**hz, "ok": False, "replica": self.name,
                    "error": f"{protocol_errors} wire protocol error(s)"}
        if float(hz.get("beat_age_s", 0.0)) > self.remote_stale_s:
            return {**hz, "ok": False, "replica": self.name,
                    "error": f"remote heartbeat stale "
                             f"{hz.get('beat_age_s')}s"}
        return hz

    def begin_drain(self, *, reason: str = "drain") -> list:
        self._set_state(DRAINING, reason=reason)
        try:
            self._client.call("drain", {"reason": reason},
                              deadline_s=self.call_timeout_s)
        except wire.WireError as e:
            # unreachable peers still drain LOCALLY: the state flip stops
            # new submits and poll() escalates to halt at grace expiry
            telemetry.emit("remote", "drain_rpc_failed", replica=self.name,
                           error=repr(e))
        return []

    def finish_drain(self, *, join_timeout_s: float = 5.0) -> list:
        self._stop_pump(join_timeout_s)
        try:
            self._client.call(
                "stop", {"mode": "drain", "final": self.proc is not None},
                deadline_s=self.call_timeout_s + join_timeout_s)
            self._collect_once()  # final harvest of finished slots
        except wire.WireError as e:
            telemetry.emit("remote", "stop_rpc_failed", replica=self.name,
                           mode="drain", error=repr(e))
        left = self._fail_pending(ReplicaDown(
            f"replica {self.name}: stopped at drain completion"))
        self._set_state(DEAD, reason="drained")
        self._reap_proc(kill=False)
        return left

    def halt(self, error: Optional[BaseException] = None, *,
             join_timeout_s: float = 5.0) -> list:
        err = (error if error is not None
               else ReplicaDown(f"replica {self.name} halted"))
        self._stop_pump(join_timeout_s)
        if not self._dead:
            try:
                self._client.call(
                    "stop", {"mode": "halt", "final": self.proc is not None},
                    deadline_s=self.call_timeout_s)
                self._collect_once()
            except wire.WireError as e:
                telemetry.emit("remote", "stop_rpc_failed",
                               replica=self.name, mode="halt",
                               error=repr(e))
        unfinished = self._fail_pending(err)
        self._set_state(DEAD, reason="halt")
        self._reap_proc(kill=True)
        return unfinished

    def close(self) -> None:
        self._stop_pump(1.0)
        self._client.close()
        self._probe.close()
        self._reap_proc(kill=True)

    # -- internals --

    def _set_state(self, new: str, *, reason: str = "") -> None:
        with self._lock:
            old = self._state_hint or self._remote["state"]
            self._state_hint = new
        if old != new:
            telemetry.emit("remote", "state", replica=self.name, frm=old,
                           to=new, reason=reason)

    def _mark_dead(self, reason: str) -> None:
        first = not self._dead
        self._dead = True
        self._dead_reason = reason
        if first:
            telemetry.emit("remote", "transport_dead", replica=self.name,
                           reason=reason)

    def _note_protocol_error(self, e: BaseException) -> None:
        with self._lock:
            self._protocol_errors += 1
            n = self._protocol_errors
        telemetry.emit("remote", "protocol_error", replica=self.name,
                       count=n, error=repr(e))

    def _note_contact(self, hb: dict) -> None:
        self.last_beat = self._time()
        with self._lock:
            for k in ("state", "beat_age_s", "busy", "ticks", "work_ticks"):
                if k in hb:
                    self._remote[k] = hb[k]
            if "backlog" in hb:
                self._remote["backlog"] = hb["backlog"]
            if "signals" in hb:
                self._remote["signals"] = hb["signals"]

    def _cached_backlog(self) -> dict:
        with self._lock:
            b = self._remote["backlog"]
            return {"queued": dict(b["queued"]),
                    "queued_total": b["queued_total"],
                    "running": b["running"]}

    def _cached_signals(self) -> dict:
        with self._lock:
            s = dict(self._remote["signals"])
        s["queued"] = dict(s.get("queued") or {})
        return s

    def _configure_spec(self, enabled: bool) -> bool:
        """Brownout rung 1 over the wire.  A transport failure leaves
        the remote state unchanged and reports the cached value — the
        autoscaler re-applies the ladder on every transition, so a
        missed toggle converges on the next apply."""
        try:
            resp = self._probe.call("configure",
                                    {"spec": bool(enabled)},
                                    deadline_s=self.call_timeout_s)
        except wire.WireError as e:
            telemetry.emit("remote", "configure_rpc_failed",
                           replica=self.name, error=repr(e))
            return bool(self._cached_signals().get("spec"))
        with self._lock:
            self._remote["signals"]["spec"] = bool(resp.get("spec"))
        return bool(resp.get("spec"))

    def _busy(self) -> bool:
        with self._lock:
            return bool(self._remote["busy"]) or bool(self._pending)

    def _submit(self, text, *, slo: str, temperature: float, key):
        if self._dead:
            raise ReplicaDown(f"remote replica {self.name} transport dead")
        if self.state in (DRAINING, DEAD):
            raise ReplicaDown(f"remote replica {self.name} is {self.state}")
        text = np.asarray(text, np.int32)
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        if key is not None:
            key = np.asarray(key, np.uint32)
            wid_src = b"|".join((text.tobytes(), key.tobytes(),
                                 slo.encode(), repr(float(temperature))
                                 .encode()))
        else:
            # no pinned key, no replay identity: a fresh nonce per call
            # (the router always pins keys; this path is direct use)
            key = np.asarray([os.getpid() & 0xFFFF, rid], np.uint32)
            wid_src = b"|".join((self.name.encode(), str(rid).encode(),
                                 str(os.getpid()).encode()))
        wid = hashlib.sha1(wid_src).hexdigest()[:20]
        handle = RemoteHandle(request_id=rid, wid=wid, slo=slo,
                              future=Future())
        # registered BEFORE the call: if the response is lost but the
        # peer executed, the pump's collect still finds a home for the
        # result — and a router re-dispatch to this same replica dedups
        # onto the same wid (exactly-once across ambiguous retries)
        with self._lock:
            self._pending[wid] = handle
        try:
            self._client.call(
                "submit", {"wid": wid, "text": text, "slo": slo,
                           "temperature": float(temperature), "key": key},
                deadline_s=self.submit_timeout_s)
        except wire.WireProtocolError as e:
            self._note_protocol_error(e)
            with self._lock:
                self._pending.pop(wid, None)
            raise ReplicaDown(
                f"remote {self.name}: protocol error on submit") from e
        except wire.WireUnavailable as e:
            self._mark_dead(f"submit connect refused: {e}")
            with self._lock:
                self._pending.pop(wid, None)
            raise ReplicaDown(
                f"remote {self.name}: unavailable on submit") from e
        except (wire.WireTimeout, wire.WireReset) as e:
            # AMBIGUOUS: the peer may or may not have executed.  Forget
            # the local handle (an orphan result is acked away by the
            # pump); the router's retry replays the same pinned key —
            # on this replica it dedups by wid, elsewhere it decodes
            # bit-identically
            with self._lock:
                self._pending.pop(wid, None)
            raise ReplicaDown(
                f"remote {self.name}: {type(e).__name__} on submit") from e
        except wire.WireRemoteError as e:
            with self._lock:
                self._pending.pop(wid, None)
            raise _map_remote_error(
                {"type": e.etype, "msg": e.msg}) from e
        self.last_beat = self._time()
        return handle

    def _pump(self) -> None:
        while not self._stop_evt.wait(self.poll_interval_s):
            if self._dead:
                return
            try:
                self._collect_once()
            except wire.WireProtocolError as e:
                self._note_protocol_error(e)
            except wire.WireUnavailable as e:
                self._mark_dead(f"collect connect refused: {e}")
                return
            except wire.WireError as e:
                # timeout/reset: transient — last_beat simply isn't
                # refreshed, and policy 2 notices if it persists
                telemetry.emit("remote", "collect_error",
                               replica=self.name, error=repr(e))

    def _collect_once(self) -> None:
        with self._lock:
            ack = sorted(self._to_ack)
        resp = self._client.call("collect", {"ack": ack},
                                 deadline_s=self.call_timeout_s)
        self._note_contact(resp)
        with self._lock:
            self._to_ack.difference_update(ack)
        for entry in resp.get("results") or ():
            wid = str(entry.get("wid"))
            with self._lock:
                handle = self._pending.pop(wid, None)
                # ack everything we saw — including orphans whose local
                # handle was abandoned after an ambiguous timeout
                self._to_ack.add(wid)
            if handle is None or handle.future.done():
                continue
            if "ok" in entry:
                handle.future.set_result(np.asarray(entry["ok"]))
            else:
                handle.future.set_exception(
                    _map_remote_error(entry.get("err") or {}))

    def _fail_pending(self, err: BaseException) -> List[RemoteHandle]:
        with self._lock:
            leftovers = list(self._pending.values())
            self._pending.clear()
        for h in leftovers:
            if not h.future.done():
                h.future.set_exception(err)
        return leftovers

    def _stop_pump(self, join_timeout_s: float) -> None:
        self._stop_evt.set()
        if self._thread is not None and self._thread.is_alive():
            if threading.current_thread() is not self._thread:
                self._thread.join(timeout=join_timeout_s)

    def _reap_proc(self, *, kill: bool) -> None:
        proc = self.proc
        if proc is None:
            return
        if proc.poll() is None and kill:
            proc.kill()
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10.0)


# --- subprocess plumbing ----------------------------------------------------


def spawn_replica(name: str, *, out_dir, slots: int = 2,
                  host_index: int = 0, metrics_port: int = 0,
                  filter_thres: float = 1.0,
                  slo_targets: Optional[Dict[str, float]] = None,
                  prefix_cache: bool = False, seed: int = 0,
                  inherit_faults: bool = False,
                  ready_timeout_s: float = 240.0,
                  **remote_kwargs) -> RemoteReplica:
    """Launch ``python -m dalle_pytorch_tpu.serve.remote`` and return a
    connected :class:`RemoteReplica` owning the child process.

    The child gets a CLEAN fault env by default (``inherit_faults=False``
    strips ``GRAFT_FAULTS``): the rpc sites inject at the CLIENT edge in
    this process, and a chaos spec meant for the parent's transport must
    not also fire inside the children."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    ready = out_dir / f"{name}.ready.json"
    if ready.exists():
        ready.unlink()
    cmd = [sys.executable, "-m", "dalle_pytorch_tpu.serve.remote",
           "--name", name, "--port", "0", "--slots", str(slots),
           "--telemetry-dir", str(out_dir / name),
           "--metrics-port", str(metrics_port),
           "--ready-file", str(ready), "--host-index", str(host_index),
           "--filter-thres", str(filter_thres), "--seed", str(seed)]
    for slo, target in (slo_targets or {}).items():
        cmd += [f"--slo-{slo}", str(target)]
    if prefix_cache:
        cmd.append("--prefix-cache")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = (str(REPO_ROOT) + os.pathsep
                         + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
    if not inherit_faults:
        env.pop("GRAFT_FAULTS", None)
    proc = subprocess.Popen(cmd, env=env, cwd=str(REPO_ROOT))
    info = _wait_ready(ready, proc, name, ready_timeout_s)
    return RemoteReplica(name, "127.0.0.1", int(info["port"]),
                         num_slots=slots, proc=proc, **remote_kwargs)


def _wait_ready(ready: Path, proc: subprocess.Popen, name: str,
                timeout_s: float) -> dict:
    pace = threading.Event()
    deadline = time.monotonic() + timeout_s
    while True:
        if ready.exists():
            try:
                return json.loads(ready.read_text())
            except ValueError:
                pass  # ready file mid-write despite atomic rename: next tick
        rc = proc.poll()
        if rc is not None:
            raise SpawnFailed(
                f"remote replica {name} exited rc={rc} before ready",
                name=name, rc=rc)
        if time.monotonic() > deadline:
            # kill AND reap: a spawn that never reached the handshake
            # must not leave an orphan child behind (it would survive
            # this process and hold its telemetry dir / ports forever)
            proc.kill()
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass  # unreapable (wedged in the kernel): raise anyway
            raise SpawnFailed(
                f"remote replica {name} not ready after {timeout_s}s "
                f"(child killed and reaped)", name=name, rc=None)
        pace.wait(0.05)


def _build_toy_model(seed: int = 0, prompts: int = 4):
    """The CI-scale toy (the fleet_smoke geometry): big enough to tick,
    small enough to compile in seconds in every child process."""
    import jax
    import jax.numpy as jnp

    from .. import DALLE, DALLEConfig, VAEConfig

    vcfg = VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                     num_layers=2, hidden_dim=8)
    cfg = DALLEConfig.from_vae(
        vcfg, dim=32, num_text_tokens=50, text_seq_len=6, depth=2, heads=2,
        dim_head=8, attn_types=("full", "axial_row"))
    dalle = DALLE(cfg)
    rng = jax.random.PRNGKey(seed)
    texts = [np.asarray(jax.random.randint(
        jax.random.PRNGKey(i), (cfg.text_seq_len,), 1, 50), np.int32)
        for i in range(prompts)]
    codes = jax.random.randint(rng, (1, cfg.image_seq_len), 0, 32)
    params = dalle.init(rng, jnp.asarray(texts[0])[None], codes,
                        return_loss=True)
    return cfg, dalle, params, texts


def main(argv=None) -> int:
    """Subprocess entry: one Replica + wire server + own obs lane."""
    import argparse

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    parser = argparse.ArgumentParser(
        description="graftwire remote replica (subprocess half)")
    parser.add_argument("--name", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--slots", type=int, default=2)
    parser.add_argument("--telemetry-dir", type=Path, required=True)
    parser.add_argument("--metrics-port", type=int, default=0)
    parser.add_argument("--ready-file", type=Path, required=True)
    parser.add_argument("--host-index", type=int, default=0)
    parser.add_argument("--filter-thres", type=float, default=1.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--slo-latency", type=float, default=None)
    parser.add_argument("--slo-throughput", type=float, default=None)
    parser.add_argument("--prefix-cache", action="store_true")
    args = parser.parse_args(argv)

    faults.install_from_env()
    reg = obs_metrics.init()
    _cfg, dalle, params, texts = _build_toy_model(seed=args.seed)
    slo_targets = {}
    if args.slo_latency is not None:
        slo_targets[LATENCY] = args.slo_latency
    if args.slo_throughput is not None:
        slo_targets[THROUGHPUT] = args.slo_throughput
    replica = Replica(
        args.name, dalle, params, args.slots,
        telemetry_dir=args.telemetry_dir, host_index=args.host_index,
        warmup_text=texts[0], filter_thres=args.filter_thres,
        seed=args.seed, slo_targets=slo_targets or None,
        prefix_cache=args.prefix_cache)
    metrics_server = obs_metrics.serve(args.metrics_port, reg,
                                       health_fn=replica.healthz)
    server = ReplicaServer(replica, port=args.port).start()
    replica.start()

    def _on_signal(signum, frame):
        server.shutdown_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    tmp = args.ready_file.with_suffix(".tmp")
    tmp.write_text(json.dumps({"port": server.port,
                               "metrics_port": metrics_server.port,
                               "pid": os.getpid()}))
    os.replace(tmp, args.ready_file)

    server.wait_shutdown()
    if replica.state != DEAD:
        replica.halt(ReplicaDown(f"replica {args.name}: process shutdown"))
    server.close()
    metrics_server.close()
    replica.close()
    faults.reset()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
