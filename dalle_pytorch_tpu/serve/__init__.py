"""Continuous-batching generation service (DESIGN.md §11).

``engine.SlotArena`` is the device half: a fixed-shape slot-structured KV
arena where admission/retirement are ``dynamic_update_slice``s and one
jitted tick decodes every occupied slot under a per-slot active mask —
never a shape change, never a retrace (gated by graftspmd's S3 serve
check).  ``scheduler.GenerationServer`` is the host half: thread-safe
request queue, iteration-level admission, SLO-aware scheduling
(latency-class requests preempt throughput-class fills), and the
per-request latency / aggregate throughput accounting ``bench_serve``
reports.
"""
from .engine import ArenaGeometry, SlotArena
from .scheduler import (LATENCY, SLO_CLASSES, THROUGHPUT, GenerationServer,
                        ServeHandle)

__all__ = [
    "ArenaGeometry", "SlotArena", "GenerationServer", "ServeHandle",
    "LATENCY", "THROUGHPUT", "SLO_CLASSES",
]
