"""Continuous-batching generation service (DESIGN.md §11, fleet tier §17).

``engine.SlotArena`` is the device half: a fixed-shape slot-structured KV
arena where admission/retirement are ``dynamic_update_slice``s and one
jitted tick decodes every occupied slot under a per-slot active mask —
never a shape change, never a retrace (gated by graftspmd's S3 serve
check).  ``scheduler.GenerationServer`` is the host half: thread-safe
request queue, iteration-level admission, SLO-aware scheduling
(latency-class requests preempt throughput-class fills), and the
per-request latency / aggregate throughput accounting ``bench_serve``
reports.

The fleet tier sits on top: ``replica.Replica`` wraps one server with a
JOINING→SERVING→DRAINING→DEAD lifecycle + driver thread, and
``router.FleetRouter`` routes over N replicas — consistent-hash
affinity with queue-depth spill, SLO-aware shedding (typed
``ShedError``), bounded retries with exponential backoff, drain/join
riding the rc-74 preemption contract, and an exactly-once future
resolution audit (zero dropped futures under replica loss).

graftwire (§21) pushes the same seam across a process boundary:
``wire`` is the stdlib framed-JSON RPC transport (typed failure
taxonomy, deadline + bounded retry + jittered backoff, ``rpc_send`` /
``rpc_recv`` fault sites), and ``remote`` pairs a subprocess-side
``ReplicaServer`` with a router-side ``RemoteReplica`` that presents
the exact ``Replica`` surface — the router needs no remote-aware code.
"""
from .autoscale import (AutoScaler, Decision, DegradeLevel, ScalePolicy,
                        Signals)
from .engine import ArenaGeometry, SlotArena
from .prefix import RadixPrefixCache
from .remote import (RemoteReplica, ReplicaServer, SpawnFailed,
                     spawn_replica)
from .replica import (DEAD, DRAINING, JOINING, SERVING, Replica,
                      ReplicaDown)
from .router import (FleetRouter, NoHealthyReplica, RequestFailed,
                     RetriesExhausted, RouterError, RouterHandle,
                     ShedError)
from .scheduler import (LATENCY, SLO_CLASSES, THROUGHPUT, GenerationServer,
                        ServeHandle, ServerStopped)
from .wire import (WireClient, WireError, WireProtocolError,
                   WireRemoteError, WireReset, WireServer, WireTimeout,
                   WireUnavailable)

__all__ = [
    "ArenaGeometry", "SlotArena", "RadixPrefixCache", "GenerationServer",
    "ServeHandle",
    "ServerStopped", "LATENCY", "THROUGHPUT", "SLO_CLASSES",
    "Replica", "ReplicaDown", "JOINING", "SERVING", "DRAINING", "DEAD",
    "FleetRouter", "RouterHandle", "RouterError", "ShedError",
    "RetriesExhausted", "RequestFailed", "NoHealthyReplica",
    "WireClient", "WireServer", "WireError", "WireTimeout",
    "WireUnavailable", "WireReset", "WireProtocolError", "WireRemoteError",
    "RemoteReplica", "ReplicaServer", "spawn_replica", "SpawnFailed",
    "AutoScaler", "Decision", "DegradeLevel", "ScalePolicy", "Signals",
]
