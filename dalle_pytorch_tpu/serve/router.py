"""FleetRouter: replica loss is a retry, not an outage.

The front end of the fleet tier (DESIGN.md §17): N :class:`Replica`
instances behind one ``submit()``, built so that **every future resolves
exactly once** — with decoded codes, a :class:`ShedError`, or a typed
:class:`RouterError` — whatever dies underneath it.  The pieces:

* **Routing** — consistent hash on the prompt bytes (crc32 ring,
  ``virtual_nodes`` points per replica) so a repeated prompt lands on the
  same replica (cache affinity: the prefix-reuse levers under ROADMAP
  direction 3 only pay off if repeats co-locate), with **queue-depth
  spill**: when the affine replica's queued backlog exceeds
  ``spill_depth`` (the PR 11 feedback signal,
  ``GenerationServer.backlog()``), the request goes to the least-loaded
  SERVING replica instead — affinity is a preference, load is a bound.
* **SLO-aware shedding** — admission compares each class's fleet-wide
  queued backlog against its bound (``shed_bounds``, default
  1×fleet-slots for ``latency``, 4× for ``throughput``: a latency-class
  request that would queue deep will miss its target anyway, so the
  honest answer is an immediate typed refusal the caller can retry
  against).  A shed future resolves with :class:`ShedError` at submit
  time — never a hang.
* **Retries** — a request on a failed or draining replica is resubmitted
  from prefill with exponential backoff, bounded by ``max_retries``;
  the per-request key is pinned at first submission, so a retried
  request replays the exact token stream the single-server path would
  have produced (the chaos gate's bit-match).  Futures are deduplicated
  by router request id: a late completion from a replica presumed dead
  is dropped, the caller's future resolves exactly once.
* **Failure detection** — three signals, three policies:

  1. *future exception* (request-scoped): a replica-side future carrying
     :class:`ServerStopped`/``InjectedFault`` is transient — retry with
     backoff; anything else is terminal for that request
     (:class:`RequestFailed`).  One bad request never condemns a replica.
  2. *heartbeat staleness* (passive, replica-scoped): a SERVING replica
     whose driver thread died or stopped beating for
     ``heartbeat_timeout_s`` is declared DEAD immediately — its in-flight
     futures are failed typed (``Replica.halt``) and resubmitted.
  3. */healthz* probe (active, replica-scoped): ``probe_failures``
     consecutive failed probes start a graceful DRAIN — stop routing
     there, let running slots finish — because a sick-but-beating
     replica deserves a drain, not a massacre.

* **Drain/join** — :meth:`drain` rides the rc-74 preemption-drill shape:
  the replica stops admitting, its queued backlog migrates immediately,
  and its running slots get ``drain_grace_s`` to finish before
  :meth:`poll` hard-halts and migrates them too.  :meth:`join` adds a
  replica under traffic: it warms (JOINING) and self-promotes to
  SERVING, at which point the hash ring includes it.

Every decision emits a ``router.*`` graftscope event and bumps
``graft_router_*`` instruments, so ``obs_report --merge`` over the
router + per-replica streams renders the fleet request flow and
``monitor --fleet --metrics`` scrapes the live state.

The monitor loop runs on a daemon thread (:meth:`start`); every pass is
one :meth:`poll` call, which tests drive directly for determinism.
"""
from __future__ import annotations

import bisect
import collections
import concurrent.futures
import dataclasses
import heapq
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import telemetry
from ..utils import faults
from ..utils import locks
from .replica import DEAD, DRAINING, SERVING, Replica, ReplicaDown
from .scheduler import LATENCY, SLO_CLASSES, THROUGHPUT, ServerStopped


class RouterError(RuntimeError):
    """Base of every terminal error a router future can carry.  The
    exactly-once contract: a future from :meth:`FleetRouter.submit`
    resolves with codes, a :class:`ShedError`, or a RouterError — never
    hangs, never resolves twice."""


class ShedError(RouterError):
    """Admission refused NOW (SLO-aware load shedding): this class's
    fleet-wide backlog exceeds its bound, so queueing would only
    manufacture an SLO miss.  Immediate and typed — the caller retries
    against it (or downgrades class); it never waits.

    ``retry_after_s`` is the router's own estimate of when the excess
    backlog will have drained, computed from the recent resolve rate —
    a caller that waits that long before resubmitting (tools/loadgen.py
    does) retries into capacity instead of hammering a full queue."""

    def __init__(self, msg: str, *, slo: Optional[str] = None,
                 depth: Optional[int] = None, bound: Optional[int] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.slo = slo
        self.depth = depth
        self.bound = bound
        self.retry_after_s = retry_after_s


class RetriesExhausted(RouterError):
    """The bounded retry budget ran out; ``__cause__`` is the last
    per-attempt failure."""


class RequestFailed(RouterError):
    """A replica failed this request with a non-transient error;
    ``__cause__`` carries it.  Not retried: a deterministic failure
    replays identically on every replica."""


class NoHealthyReplica(RouterError):
    """No SERVING replica at dispatch time (transient inside the retry
    path: a rolling restart's empty window; terminal only when it
    exhausts the retry budget)."""


@dataclasses.dataclass
class RouterHandle:
    """One routed request: the caller-facing future + audit trail."""

    request_id: int
    slo: str
    future: concurrent.futures.Future
    submitted_at: float = 0.0
    # (replica, attempt) per dispatch — the migration story of this
    # request, readable after the fact (tests pin affinity/spill on it)
    trail: List[Tuple[str, int]] = dataclasses.field(default_factory=list)

    def result(self, timeout: Optional[float] = None):
        """Decoded codes [image_seq_len]; raises the typed terminal error
        otherwise.  Resolves exactly once — see :class:`RouterError`."""
        return self.future.result(timeout)


@dataclasses.dataclass
class _Tracked:
    """Router-side state of one in-flight request."""

    handle: RouterHandle
    text: np.ndarray
    slo: str
    temperature: float
    key: np.ndarray            # pinned at submit: retries replay it
    attempts: int = 0          # dispatches so far
    replica: Optional[str] = None
    resolved: bool = False


# default shed bounds as multiples of the serving fleet's slot count
_SHED_FACTORS = {LATENCY: 1.0, THROUGHPUT: 4.0}


class FleetRouter:
    """Front end over N in-process :class:`Replica` instances (the
    chip-free tier; each replica is one arena + driver thread)."""

    def __init__(self, replicas=(), *, seed: int = 0,
                 virtual_nodes: int = 32, spill_depth: int = 4,
                 shed_bounds: Optional[Dict[str, int]] = None,
                 max_retries: int = 3, retry_backoff_s: float = 0.05,
                 retry_backoff_cap_s: float = 1.0,
                 heartbeat_timeout_s: float = 5.0,
                 probe_every_s: float = 0.25, probe_failures: int = 3,
                 drain_grace_s: float = 10.0,
                 monitor_interval_s: float = 0.02,
                 time_fn=time.monotonic):
        self._time = time_fn
        self._seed = int(seed)
        self.virtual_nodes = int(virtual_nodes)
        self.spill_depth = int(spill_depth)
        self.shed_bounds = dict(shed_bounds) if shed_bounds else None
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_cap_s = float(retry_backoff_cap_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.probe_every_s = float(probe_every_s)
        self.probe_failures = int(probe_failures)
        self.drain_grace_s = float(drain_grace_s)
        self.monitor_interval_s = float(monitor_interval_s)

        self._lock = locks.TracedLock("router")
        # admission shed factors, overridable fleet-wide at runtime (the
        # graftscale brownout ladder's actuation surface — §22); explicit
        # shed_bounds still win when set
        self._shed_factors: Dict[str, float] = dict(_SHED_FACTORS)
        self._replicas: Dict[str, Replica] = {}
        # DRAINING predecessors superseded by a same-name join: out of
        # the by-name table (the ring can never double-count the name)
        # but still owed their grace-window accounting in poll()
        self._retired: List[Replica] = []
        self._tracked: Dict[int, _Tracked] = {}
        self._retries: List[Tuple[float, int]] = []   # heap of (due, rid)
        # drain grace deadlines keyed by OBJECT identity, not name: a
        # successor joining under the same name must never inherit (or
        # clobber) its predecessor's deadline
        self._drains: Dict[int, float] = {}
        self._probe_fail: Dict[str, int] = {}
        # resolve timestamps (ok or err — either frees capacity): the
        # drain-rate window behind ShedError.retry_after_s
        self._resolve_times: collections.deque = collections.deque(
            maxlen=32)
        self._last_probe = float("-inf")
        self._next_rid = 0
        self._closing = False
        self._stop_evt = threading.Event()
        self._monitor: Optional[threading.Thread] = None

        # audit counters (the zero-dropped-futures ledger)
        self.resolved_ok = 0
        self.resolved_err = 0
        self.retries_total = 0
        self.replica_deaths = 0
        self.shed = {slo: 0 for slo in SLO_CLASSES}

        for r in replicas:
            self.add_replica(r, start=False)

    # --- membership --------------------------------------------------------

    def add_replica(self, replica: Replica, *, start: bool = True
                    ) -> Replica:
        """Register (and by default start) a replica.  It takes traffic
        only once its own driver promotes it to SERVING.

        A join under a name whose current holder is DRAINING or DEAD is
        the rolling-restart race: the predecessor RETIRES — it leaves
        the by-name table (so the hash ring can never carry the name
        twice) but keeps its identity-keyed drain deadline, and poll()
        walks it to completion like any other drain."""
        with self._lock:
            prev = self._replicas.get(replica.name)
            if prev is not None:
                assert prev is not replica, \
                    f"replica {replica.name} already registered"
                assert prev.state in (DRAINING, DEAD), (
                    f"replica name {replica.name!r} is still "
                    f"{prev.state}; drain it before joining a successor")
                if prev.state == DRAINING:
                    self._retired.append(prev)
            self._replicas[replica.name] = replica
            self._probe_fail[replica.name] = 0
        self._emit("router", "replica_join", replica=replica.name,
                   superseded=prev is not None)
        if start and replica._thread is None:
            replica.start()
        return replica

    def join(self, replica: Replica) -> Replica:
        """Add a replica under traffic (alias of :meth:`add_replica` with
        start=True — the rolling-restart read)."""
        return self.add_replica(replica, start=True)

    def replica(self, name: str) -> Replica:
        with self._lock:
            return self._replicas[name]

    def replicas(self) -> List[Replica]:
        """Snapshot of the registered membership (retired same-name
        predecessors excluded) — the autoscaler's observation surface."""
        with self._lock:
            return list(self._replicas.values())

    def _serving(self) -> List[Replica]:
        with self._lock:
            reps = list(self._replicas.values())
        return [r for r in reps if r.state == SERVING and r.alive()]

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetRouter":
        """Start every not-yet-started replica and the monitor thread."""
        with self._lock:
            reps = list(self._replicas.values())
        for r in reps:
            if r._thread is None:
                r.start()
        if self._monitor is None:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="fleet-router-monitor",
                daemon=True)
            self._monitor.start()
        return self

    def wait_serving(self, n: int = 1, timeout_s: float = 30.0) -> None:
        """Block until ``n`` replicas are SERVING (warm) or raise.  Waits
        on the router's stop event rather than a bare sleep, so a close()
        racing the warm-up unblocks the caller immediately (THR002: poll
        loops wait on an Event, never sleep against shared state)."""
        deadline = self._time() + timeout_s
        while len(self._serving()) < n:
            if self._closing:
                raise RouterError("router closed while waiting for "
                                  "replicas to warm")
            if self._time() > deadline:
                raise RuntimeError(
                    f"{len(self._serving())}/{n} replicas serving after "
                    f"{timeout_s}s")
            self._stop_evt.wait(0.005)

    def close(self) -> None:
        """Stop monitoring, halt every live replica, and fail any still
        unresolved future with a typed RouterError — closing the router
        upholds the never-hang contract too."""
        self._closing = True
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._lock:
            reps = list(self._replicas.values()) + list(self._retired)
        for r in reps:
            if r.state != DEAD:
                r.halt(ReplicaDown(f"replica {r.name}: router closed"))
        with self._lock:
            leftovers = list(self._tracked.values())
        for t in leftovers:
            err = RouterError("router closed with the request unresolved")
            self._reject(t, err)
        for r in reps:
            r.close()

    # --- submission --------------------------------------------------------

    def submit(self, text, *, slo: str = THROUGHPUT,
               temperature: float = 1.0, key=None) -> RouterHandle:
        """Route one request into the fleet (thread-safe).  The returned
        handle's future resolves EXACTLY ONCE: decoded codes, a
        :class:`ShedError` (immediate, at submit), or a
        :class:`RouterError` after the retry budget — never a hang."""
        if slo not in SLO_CLASSES:
            raise ValueError(f"unknown SLO class {slo!r}; one of "
                             f"{SLO_CLASSES}")
        text = np.asarray(text, np.int32)
        if text.ndim == 1:
            text = text[None]
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        handle = RouterHandle(request_id=rid, slo=slo,
                              future=concurrent.futures.Future(),
                              submitted_at=self._time())
        tracked = _Tracked(
            handle=handle, text=text, slo=slo,
            temperature=float(temperature),
            # the key is pinned HERE so every retry replays the same
            # stream — the bit-match-after-migration invariant
            key=(np.asarray(key, np.uint32) if key is not None
                 else np.asarray([self._seed, rid], np.uint32)))
        self._emit("router", "submit", rid=rid, slo=slo)
        reg = obs_metrics.active()
        if reg is not None:
            reg.counter("graft_router_submitted_total",
                        "requests entering the router", slo=slo).inc()
        bound, depth = self._shed_check(slo)
        if bound is not None and depth >= bound:
            retry_after = self._shed_retry_after(depth, bound)
            err = ShedError(
                f"shed: {slo} fleet backlog {depth} >= bound {bound} "
                f"(retry after {retry_after:.2f}s)",
                slo=slo, depth=depth, bound=bound,
                retry_after_s=retry_after)
            with self._lock:
                self.shed[slo] += 1
            self._emit("router", "shed", rid=rid, slo=slo, depth=depth,
                       bound=bound, retry_after_s=round(retry_after, 4))
            if reg is not None:
                reg.counter("graft_router_shed_total",
                            "requests shed at admission", slo=slo).inc()
            handle.future.set_exception(err)
            return handle
        with self._lock:
            self._tracked[rid] = tracked
        self._set_inflight_gauge()
        self._dispatch(tracked)
        return handle

    def _shed_check(self, slo: str) -> Tuple[Optional[int], int]:
        """(bound, current fleet-wide queued depth) for one SLO class;
        bound None when there is no serving capacity to measure against
        (admission then rides the bounded retry path instead)."""
        reps = self._serving()
        if not reps:
            return None, 0
        depth = sum(r.server.backlog()["queued"][slo] for r in reps)
        bound = (self.shed_bounds or {}).get(slo)
        if bound is None:
            slots = sum(r.num_slots for r in reps)
            with self._lock:
                factor = self._shed_factors.get(slo, _SHED_FACTORS[slo])
            # factor 0 is the brownout ladder's full-shed rung: bound 0
            # makes depth >= bound ALWAYS true — every admission in this
            # class sheds typed and fast instead of queuing to time out
            bound = max(1, int(factor * slots)) if factor > 0.0 else 0
        return bound, depth

    def set_shed_factors(self, factors: Optional[Dict[str, float]] = None
                         ) -> None:
        """Override the per-class admission shed factors fleet-wide —
        the brownout ladder's reversible actuation surface.  Keys absent
        from ``factors`` fall back to the defaults; ``None`` restores
        them entirely; a factor of 0 sheds EVERYTHING in that class.
        Explicit constructor ``shed_bounds`` still take precedence."""
        merged = dict(_SHED_FACTORS)
        merged.update(factors or {})
        with self._lock:
            changed = merged != self._shed_factors
            self._shed_factors = merged
        if changed:
            self._emit("router", "shed_factors",
                       **{slo: merged[slo] for slo in SLO_CLASSES})

    def shed_factors(self) -> Dict[str, float]:
        """The effective per-class shed factors (a restarted autoscaler
        reads the current brownout rung back off these)."""
        with self._lock:
            return dict(self._shed_factors)

    def _shed_retry_after(self, depth: int, bound: int) -> float:
        """Backlog-drain-rate hint: (excess depth) / (recent resolve
        rate), clamped to [10ms, 30s].  With no recent resolutions to
        rate (cold start, stalled fleet) the hint is a flat 250ms — a
        guess that keeps the caller honest without a thundering herd."""
        with self._lock:
            window = list(self._resolve_times)
        now = self._time()
        if len(window) >= 2:
            span = now - window[0]
            if span > 0:
                rate = len(window) / span
                excess = max(1, depth - bound + 1)
                return float(min(max(excess / rate, 0.01), 30.0))
        return 0.25

    # --- routing -----------------------------------------------------------

    def _ring_for(self, reps: List[Replica]) -> List[Tuple[int, str]]:
        ring = []
        for r in reps:
            for v in range(self.virtual_nodes):
                ring.append((zlib.crc32(f"{r.name}#{v}".encode())
                             & 0xFFFFFFFF, r.name))
        ring.sort()
        return ring

    def _route(self, tracked: _Tracked) -> Replica:
        """Affine replica by consistent hash, spilled to the least-loaded
        one when the affine queue is deeper than ``spill_depth``."""
        reps = self._serving()
        if not reps:
            raise NoHealthyReplica("no serving replica")
        by_name = {r.name: r for r in reps}
        ring = self._ring_for(reps)
        point = zlib.crc32(tracked.text.tobytes()) & 0xFFFFFFFF
        i = bisect.bisect_left(ring, (point, "")) % len(ring)
        affine = by_name[ring[i][1]]
        if len(reps) > 1:
            loads = {r.name: r.server.backlog() for r in reps}
            if loads[affine.name]["queued_total"] > self.spill_depth:
                spill = min(reps, key=lambda r: (
                    loads[r.name]["queued_total"] + loads[r.name]["running"],
                    r.name))
                if spill.name != affine.name:
                    self._emit("router", "spill",
                               rid=tracked.handle.request_id,
                               frm=affine.name, to=spill.name,
                               depth=loads[affine.name]["queued_total"])
                    return spill
        return affine

    def _dispatch(self, tracked: _Tracked) -> None:
        tracked.attempts += 1
        attempt = tracked.attempts
        try:
            faults.fire("router_submit")
            replica = self._route(tracked)
            sub = replica.server.submit(
                tracked.text, slo=tracked.slo,
                temperature=tracked.temperature, key=tracked.key)
        except (faults.InjectedFault, ServerStopped, NoHealthyReplica) as e:
            # transient dispatch failure: injected, raced a drain/stop,
            # or an empty rotation — back off and retry, bounded
            self._schedule_retry(tracked, e)
            return
        tracked.replica = replica.name
        tracked.handle.trail.append((replica.name, attempt))
        rid = tracked.handle.request_id
        self._emit("router", "dispatch", rid=rid, replica=replica.name,
                   attempt=attempt, sub_rid=sub.request_id)
        sub.future.add_done_callback(
            lambda f, rid=rid: self._on_done(rid, f))

    # --- resolution (exactly once) -----------------------------------------

    def _on_done(self, rid: int, f: concurrent.futures.Future) -> None:
        with self._lock:
            tracked = self._tracked.get(rid)
        if tracked is None or tracked.resolved:
            # dedup by request id: a late completion from a replica
            # presumed dead arrives AFTER the retry resolved the future —
            # dropped, the caller saw exactly one resolution
            return
        exc = f.exception()
        if exc is None:
            self._resolve(tracked, f.result(0))  # done: never waits
        elif isinstance(exc, (ServerStopped, faults.InjectedFault)):
            # the replica died/drained under the request, or an injected
            # transient hit it mid-decode: resubmit from prefill elsewhere
            self._schedule_retry(tracked, exc)
        else:
            err = RequestFailed(
                f"request {rid} failed non-transiently on "
                f"{tracked.replica}: {exc!r}")
            err.__cause__ = exc
            self._reject(tracked, err)

    def _schedule_retry(self, tracked: _Tracked, exc: BaseException) -> None:
        rid = tracked.handle.request_id
        if self._closing:
            err = RouterError("router closed while retrying")
            err.__cause__ = exc
            self._reject(tracked, err)
            return
        if tracked.attempts > self.max_retries:
            err = RetriesExhausted(
                f"request {rid}: {tracked.attempts} attempts failed "
                f"(max_retries={self.max_retries}); last: {exc!r}")
            err.__cause__ = exc
            self._reject(tracked, err)
            return
        delay = min(self.retry_backoff_s * (2 ** (tracked.attempts - 1)),
                    self.retry_backoff_cap_s)
        due = self._time() + delay
        with self._lock:
            heapq.heappush(self._retries, (due, rid))
            self.retries_total += 1
        self._emit("router", "retry", rid=rid, attempt=tracked.attempts,
                   delay_s=round(delay, 4), replica=tracked.replica,
                   error=repr(exc))
        reg = obs_metrics.active()
        if reg is not None:
            reg.counter("graft_router_retries_total",
                        "request resubmissions").inc()

    def _resolve(self, tracked: _Tracked, codes) -> None:
        with self._lock:
            if tracked.resolved:
                return
            tracked.resolved = True
            self._tracked.pop(tracked.handle.request_id, None)
            self.resolved_ok += 1
            self._resolve_times.append(self._time())
        self._emit("router", "resolve", rid=tracked.handle.request_id,
                   replica=tracked.replica, attempts=tracked.attempts,
                   latency_s=self._time() - tracked.handle.submitted_at)
        self._count_outcome("ok", tracked.slo)
        tracked.handle.future.set_result(codes)

    def _reject(self, tracked: _Tracked, err: BaseException) -> None:
        with self._lock:
            if tracked.resolved:
                return
            tracked.resolved = True
            self._tracked.pop(tracked.handle.request_id, None)
            self.resolved_err += 1
            self._resolve_times.append(self._time())
        self._emit("router", "fail", rid=tracked.handle.request_id,
                   replica=tracked.replica, attempts=tracked.attempts,
                   error=repr(err))
        self._count_outcome("error", tracked.slo)
        tracked.handle.future.set_exception(err)

    def _count_outcome(self, outcome: str, slo: str) -> None:
        self._set_inflight_gauge()
        reg = obs_metrics.active()
        if reg is not None:
            reg.counter("graft_router_resolved_total",
                        "router futures resolved", outcome=outcome,
                        slo=slo).inc()

    def _set_inflight_gauge(self) -> None:
        reg = obs_metrics.active()
        if reg is not None:
            with self._lock:
                n = len(self._tracked)
            reg.gauge("graft_router_inflight",
                      "requests admitted and not yet resolved").set(n)

    # --- health / drain monitoring -----------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop_evt.wait(self.monitor_interval_s):
            try:
                self.poll()
            # graftlint: disable=EXC001 (the monitor must survive any single poll error; it is reported in-band as a router event and the next pass retries)
            except Exception as e:
                self._emit("router", "monitor_error", error=repr(e))

    def poll(self) -> None:
        """One monitor pass: detect dead replicas (heartbeat), probe
        health, account drain grace, release due retries.  The monitor
        thread calls this every ``monitor_interval_s``; tests call it
        directly for determinism."""
        now = self._time()
        with self._lock:
            reps = list(self._replicas.values()) + list(self._retired)
        for r in reps:
            state = r.state
            if state == SERVING and (
                    not r.alive()
                    or r.beat_age() > self.heartbeat_timeout_s):
                # policy 2 — heartbeat: the driver is a corpse (thread
                # dead) or wedged past the timeout; immediate DEAD, every
                # in-flight future failed typed, migrated by the retries
                reason = ("driver thread died" if not r.alive()
                          else f"heartbeat stale {r.beat_age():.2f}s")
                self._declare_dead(r, reason=reason)
            elif state == DRAINING:
                with self._lock:
                    deadline = self._drains.get(id(r))
                # finish_drain/halt join the driver thread — they must run
                # OUTSIDE the lock (the done-callbacks they trigger take it)
                if not r.server.busy:
                    left = r.finish_drain()
                    self._drain_done(r)
                    self._emit("router", "drain_complete", replica=r.name,
                               in_grace=True, migrated=len(left))
                elif deadline is not None and now > deadline:
                    unfinished = r.halt(ReplicaDown(
                        f"replica {r.name}: drain grace expired"))
                    self._drain_done(r)
                    self._emit("router", "drain_expired", replica=r.name,
                               migrated=len(unfinished))
            elif state == DEAD:
                self._drain_done(r)  # retired corpse: drop the accounting
        if now - self._last_probe >= self.probe_every_s:
            self._last_probe = now
            self.audit()  # refresh the live ledger gauges at probe cadence
            for r in reps:
                if r.state != SERVING:
                    continue
                # policy 3 — active probe: consecutive failures start a
                # graceful drain (quarantine), never an instant kill — a
                # sick-but-beating replica can still finish its slots
                hz = r.healthz()
                if hz.get("ok"):
                    with self._lock:
                        self._probe_fail[r.name] = 0
                else:
                    with self._lock:
                        n = self._probe_fail[r.name] = \
                            self._probe_fail.get(r.name, 0) + 1
                    self._emit("router", "probe_fail", replica=r.name,
                               consecutive=n)
                    if n >= self.probe_failures:
                        # drain THIS object (not the name): a successor
                        # may already hold the name in the table
                        self._drain_replica(
                            r, reason=f"healthz failed x{n}")
        due: List[int] = []
        with self._lock:
            while self._retries and self._retries[0][0] <= now:
                due.append(heapq.heappop(self._retries)[1])
        for rid in due:
            with self._lock:
                tracked = self._tracked.get(rid)
            if tracked is not None and not tracked.resolved:
                self._dispatch(tracked)

    def _declare_dead(self, replica: Replica, *, reason: str) -> None:
        with self._lock:
            self.replica_deaths += 1
        telemetry.note(
            "router", "replica_dead",
            f"replica {replica.name} declared dead ({reason}); migrating "
            "its in-flight requests", prefix="[router]",
            replica=replica.name, reason=reason)
        reg = obs_metrics.active()
        if reg is not None:
            reg.counter("graft_router_replica_deaths_total",
                        "replicas declared dead", replica=replica.name
                        ).inc()
        # halt fails every in-flight future with ReplicaDown; the done
        # callbacks schedule their retries before halt returns
        replica.halt(ReplicaDown(
            f"replica {replica.name} dead ({reason})"))

    def drain(self, name: str, *, grace_s: Optional[float] = None,
              reason: str = "operator drain") -> Replica:
        """Begin draining ``name``'s CURRENT holder: stop admitting,
        migrate the queued backlog now, give running slots ``grace_s``
        (default ``drain_grace_s``) to finish before :meth:`poll`
        hard-halts and migrates them too — the rc-74 notice/grace/kill
        contract applied to serving."""
        with self._lock:
            replica = self._replicas[name]
        return self._drain_replica(replica, grace_s=grace_s, reason=reason)

    def _drain_replica(self, replica: Replica, *,
                       grace_s: Optional[float] = None,
                       reason: str = "operator drain") -> Replica:
        grace = self.drain_grace_s if grace_s is None else float(grace_s)
        with self._lock:
            self._drains[id(replica)] = self._time() + grace
        self._emit("router", "drain_begin", replica=replica.name,
                   grace_s=grace, reason=reason)
        replica.begin_drain(reason=reason)
        return replica

    def _drain_done(self, replica: Replica) -> None:
        """Forget a finished drain: its identity-keyed deadline and (for
        a superseded predecessor) its retirement slot."""
        with self._lock:
            self._drains.pop(id(replica), None)
            if replica in self._retired:
                self._retired.remove(replica)

    # --- accounting --------------------------------------------------------

    def audit(self) -> dict:
        """The zero-dropped-futures ledger: ``submitted == resolved_ok +
        resolved_err + shed + outstanding`` must always hold
        (``balanced``); the chaos gate asserts it with outstanding == 0
        after the traffic settles."""
        with self._lock:
            outstanding = len(self._tracked)
            submitted = self._next_rid
            shed_total = sum(self.shed.values())
            out = dict(
                submitted=submitted, resolved_ok=self.resolved_ok,
                resolved_err=self.resolved_err, shed=shed_total,
                shed_by_class=dict(self.shed), outstanding=outstanding,
                retries=self.retries_total,
                replica_deaths=self.replica_deaths,
                balanced=(submitted == self.resolved_ok + self.resolved_err
                          + shed_total + outstanding))
        self._publish_audit_gauges(out)
        return out

    def _publish_audit_gauges(self, a: dict) -> None:
        """Mirror the ledger onto /metrics so its balance is visible
        LIVE (the autoscaler's shed-rate input; ``monitor --fleet``
        prints the same line from the scrape side).  The family is
        ``graft_router_audit_*``: ``graft_router_submitted_total`` /
        ``_shed_total`` already exist as per-slo event COUNTERS, and the
        registry (correctly) refuses to re-register a name under a
        different kind — the ledger needs point-in-time gauges."""
        reg = obs_metrics.active()
        if reg is None:
            return
        for field, value in (("submitted", a["submitted"]),
                             ("ok", a["resolved_ok"]),
                             ("err", a["resolved_err"]),
                             ("shed", a["shed"]),
                             ("outstanding", a["outstanding"])):
            reg.gauge(f"graft_router_audit_{field}_total",
                      f"audit ledger: {field}").set(value)
        reg.gauge("graft_router_audit_balanced",
                  "1 iff submitted == ok + err + shed + outstanding"
                  ).set(int(a["balanced"]))

    def stats(self) -> dict:
        """Fleet snapshot: per-replica lifecycle + load, plus the audit
        ledger — what ``monitor --fleet --metrics`` renders from the
        scrape side."""
        with self._lock:
            reps = list(self._replicas.values())
        return dict(
            replicas={r.name: dict(state=r.state, alive=r.alive(),
                                   beat_age_s=round(r.beat_age(), 3),
                                   ticks=r.ticks, **r.server.backlog())
                      for r in reps},
            **self.audit())

    def _emit(self, kind: str, name: str, **fields):
        return telemetry.emit(kind, name, **fields)
