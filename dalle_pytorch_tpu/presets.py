"""Scaled geometry presets — the ROADMAP's model-scale ladder.

One place naming the (config geometry, ParallelPlan) pairs a run can ask
for by name, so ``train_dalle.py``'s hard-coded CUB block is one preset
of many and the analysis suite can gate rungs that do not fit a single
chip.  Four rungs today:

==========  ======  ========  =======================================
preset      params  geometry  role
==========  ======  ========  =======================================
tiny        ~0.04M  dim-32    tests / smoke (chip-free twins)
cub         ~15M    dim-256   the production CUB-200 run (PR 1..14)
cub-512     ~345M   dim-512   first scale rung where HBM genuinely
                              binds: S4 says ~13.2 GiB/device under
                              fsdp-4 vs v5e-4's 14.4 GiB budget
cub-1024    ~1.3B   dim-1024  the MFU rung (ROADMAP direction 1):
                              4096 image tokens (fmap-64), the first
                              geometry where arithmetic intensity
                              crosses the v5e ridge and fsdp-x-tp /
                              dcn-hybrid plan choices diverge —
                              graftplan's autotuner sweep lives here
==========  ======  ========  =======================================

``cub-512`` and ``cub-1024`` are ALSO :data:`~dalle_pytorch_tpu.parallel.
plan.PLAN_REGISTRY` entries (fsdp-4, and the fsdp-4 x tp-2 hybrid
respectively — the ZeRO/tensor shardings that make those counts fit at
all): registry name and config preset resolve together via
:data:`SCALE_PRESETS`.  Scale-preset registry entries are excluded from
``tools/spmd_check.py``'s default per-push matrix (their S4 compile at
opt0 takes ~8 minutes at dim-512) — ``spmd_check --presets`` runs the
full S4 HBM proof, and the nightly CI job carries it; contract_check
covers the cheap half (geometry instantiates, param count in band,
shardings lower) on every push, and ``tools/graftmem.py`` commits the
rung's walker-only memory timeline to the perf ledger.

Config factories import jax lazily: ``tools/spmd_check.py`` must set its
platform env BEFORE anything touches jax, and it imports this module.
"""
from __future__ import annotations

import functools

#: Param-count acceptance bands (min, max) per preset — contract_check's
#: cheap chip-free gate that a geometry edit doesn't silently change the
#: rung's scale class.
PARAM_BANDS = {
    "tiny": (0.01e6, 1e6),
    "cub": (10e6, 25e6),
    "cub-512": (300e6, 400e6),
    "cub-1024": (1.15e9, 1.45e9),
}


def tiny_config(**overrides):
    """Small geometry: seq 24 (divisible by sp=2), heads 4 (divisible by
    the ulysses sp axis), depth 2 (divisible by pp=2)."""
    from dalle_pytorch_tpu import DALLEConfig

    base = dict(dim=32, depth=2, heads=4, dim_head=8, num_text_tokens=50,
                text_seq_len=8, num_image_tokens=32, image_size=64,
                image_fmap_size=4)
    base.update(overrides)
    return DALLEConfig(**base)


def cub_config(**overrides):
    """The production CUB-200 geometry (bench.py::cub200_config shapes)
    at the checkpoint-eval dtype (f32 activations)."""
    from dalle_pytorch_tpu import DALLEConfig

    base = dict(dim=256, depth=8, heads=8, dim_head=64,
                num_text_tokens=7800, text_seq_len=80,
                num_image_tokens=1024, image_size=256, image_fmap_size=32)
    base.update(overrides)
    return DALLEConfig(**base)


def cub512_config(**overrides):
    """The dim-512 scale rung (~345M params): same CUB data geometry
    (80-token captions, 32x32 code grid), transformer widened to dim-512
    and deepened to 80 layers — the first rung where the S4 budget
    genuinely binds (fsdp-4: ~13.2 GiB/device live vs v5e-4's
    0.9 x 16 GiB) rather than fitting everywhere trivially."""
    from dalle_pytorch_tpu import DALLEConfig

    base = dict(dim=512, depth=80, heads=8, dim_head=64,
                num_text_tokens=7800, text_seq_len=80,
                num_image_tokens=1024, image_size=256, image_fmap_size=32)
    base.update(overrides)
    return DALLEConfig(**base)


def cub1024_config(**overrides):
    """The dim-1024 MFU rung (~1.3B params): captions unchanged but the
    code grid doubled to 64x64 (4096 image tokens — a finer VAE stride at
    the same 256px crops), dim-1024 x 76 layers x 16 heads.  This is the
    first geometry where the roofline's arithmetic intensity crosses the
    v5e ridge (~240 FLOP/byte) and plan choice genuinely matters: pure
    fsdp no longer fits the S4 budget at batch 8, the fsdp-4 x tp-2
    hybrid does, and on multi-slice topologies the dcn placement of the
    grad all-reduce decides whether the step is ICI- or DCN-bound
    (tools/plan_search.py sweeps exactly those choices).

    ``use_remat`` is ON at this rung: without per-block rematerialization
    the backward pass keeps every block's activations live and the
    compiled S4 estimate shows ~216 GiB/device of XLA temporaries at
    batch 8 — no chip holds that.  Remat trades the recompute (the
    roofline is byte-bound here anyway) for per-layer-bounded liveness:
    the jaxpr walker's peak drops 2541 -> 86 GiB global (~10.7
    GiB/device under the hybrid plan).  Note the *opt0 compiled*
    estimate still reads ~132 GiB/device — opt0 buffer assignment does
    not reuse buffers across remat regions, so it sums all 76 blocks —
    which is why spmd_check.S4_PRESET_EXPECT declares this rung "over"
    and gates the compiled proof as a drift sentinel rather than a fit
    proof (the walker + P3 own the fit verdict here)."""
    from dalle_pytorch_tpu import DALLEConfig

    base = dict(dim=1024, depth=76, heads=16, dim_head=64,
                num_text_tokens=7800, text_seq_len=80,
                num_image_tokens=1024, image_size=256, image_fmap_size=64,
                use_remat=True)
    base.update(overrides)
    return DALLEConfig(**base)


#: Every named config geometry (CLI ``--preset`` surface).
CONFIG_PRESETS = {
    "tiny": tiny_config,
    "cub": cub_config,
    "cub-512": cub512_config,
    "cub-1024": cub1024_config,
}

#: The scale rungs that are ALSO plan-registry entries: registry name ->
#: config factory.  tools/spmd_check.py excludes these names from its
#: default per-push matrix and proves them under ``--presets``.
SCALE_PRESETS = {
    "cub-512": cub512_config,
    "cub-1024": cub1024_config,
}


def preset_config(name: str, **overrides):
    """Resolve a preset name to its config (ValueError on unknown)."""
    if name not in CONFIG_PRESETS:
        raise ValueError(f"unknown preset {name!r}; known: "
                         f"{sorted(CONFIG_PRESETS)}")
    return CONFIG_PRESETS[name](**overrides)


@functools.lru_cache(maxsize=None)
def preset_param_count(name: str) -> int:
    """Chip-free param count of a preset's DALLE (eval_shape — nothing
    executes).  Pure per name (presets take no free parameters), so the
    eval_shape trace — seconds at dim-1024 — runs once per process even
    when several gates band-check the same rung."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu import DALLE

    cfg = preset_config(name)
    dalle = DALLE(cfg)
    text = jax.ShapeDtypeStruct((1, cfg.text_seq_len), jnp.int32)
    codes = jax.ShapeDtypeStruct((1, cfg.image_seq_len), jnp.int32)
    params = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                            codes)["params"]
    return sum(int(leaf.size) for leaf in jax.tree.leaves(params))


def check_param_band(name: str) -> str:
    """contract_check's preset gate: the param count sits inside the
    rung's declared band.  Returns the PASS detail; raises ValueError."""
    lo, hi = PARAM_BANDS[name]
    n = preset_param_count(name)
    if not lo <= n <= hi:
        raise ValueError(
            f"preset {name!r}: {n / 1e6:.1f}M params outside the declared "
            f"band [{lo / 1e6:.0f}M, {hi / 1e6:.0f}M] — a geometry edit "
            "changed the rung's scale class; update presets.PARAM_BANDS "
            "deliberately if intended")
    return f"{n / 1e6:.1f}M params in band [{lo / 1e6:.0f}M, {hi / 1e6:.0f}M]"
