"""CLIP — dual-encoder contrastive model (trainable) in JAX.

Capability parity with the reference `CLIP`
(`/root/reference/dalle_pytorch/dalle_pytorch.py:209-285`): text transformer
encoder + ViT-style patch transformer encoder, masked-mean text pooling,
L2-normalized latents, learned (exp) temperature, symmetric cross-entropy.

Used both as a trainable model (`train` parity) and as the re-ranking scorer
hook in generation (ref generate_images clip scoring :422-424, genrank.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.transformer import Transformer
from ..utils.helpers import l2norm, masked_mean


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    """Mirrors the reference ctor kwargs (dalle_pytorch.py:209-226)."""

    dim_text: int = 512
    dim_image: int = 512
    dim_latent: int = 512
    num_text_tokens: int = 10000
    text_enc_depth: int = 6
    text_seq_len: int = 256
    text_heads: int = 8
    num_visual_tokens: int = 512
    visual_enc_depth: int = 6
    visual_heads: int = 8
    visual_image_size: int = 256
    visual_patch_size: int = 32
    channels: int = 3
    dtype: Any = jnp.float32

    def __post_init__(self):
        assert self.visual_image_size % self.visual_patch_size == 0, (
            "Image dimensions must be divisible by the patch size."
        )

    @property
    def num_patches(self) -> int:
        return (self.visual_image_size // self.visual_patch_size) ** 2

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("dtype")
        return d

    @classmethod
    def from_dict(cls, d: dict, **overrides) -> "CLIPConfig":
        d = dict(d)
        d.update(overrides)
        return cls(**d)


class CLIP(nn.Module):
    cfg: CLIPConfig

    def setup(self):
        cfg = self.cfg
        emb_init = nn.initializers.normal(1.0)
        self.text_emb = nn.Embed(cfg.num_text_tokens, cfg.dim_text,
                                 embedding_init=emb_init, name="text_emb")
        self.text_pos_emb = nn.Embed(cfg.text_seq_len, cfg.dim_text,
                                     embedding_init=emb_init, name="text_pos_emb")
        self.text_transformer = Transformer(
            dim=cfg.dim_text, depth=cfg.text_enc_depth, seq_len=cfg.text_seq_len,
            causal=False, heads=cfg.text_heads, dtype=cfg.dtype,
            name="text_transformer")
        self.to_text_latent = nn.Dense(cfg.dim_latent, use_bias=False,
                                       dtype=jnp.float32, name="to_text_latent")

        self.to_visual_embedding = nn.Dense(cfg.dim_image, dtype=cfg.dtype,
                                            name="to_visual_embedding")
        self.visual_pos_emb = nn.Embed(cfg.num_patches, cfg.dim_image,
                                       embedding_init=emb_init, name="visual_pos_emb")
        self.visual_transformer = Transformer(
            dim=cfg.dim_image, depth=cfg.visual_enc_depth, seq_len=cfg.num_patches,
            causal=False, heads=cfg.visual_heads, dtype=cfg.dtype,
            name="visual_transformer")
        self.to_visual_latent = nn.Dense(cfg.dim_latent, use_bias=False,
                                         dtype=jnp.float32, name="to_visual_latent")

        self.temperature = self.param("temperature", nn.initializers.ones, ())

    def _patchify(self, image):
        """[b, H, W, C] -> [b, num_patches, p*p*C] (ref einops patchify :257)."""
        p = self.cfg.visual_patch_size
        b, H, W, C = image.shape
        h, w = H // p, W // p
        x = image.reshape(b, h, p, w, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(b, h * w, p * p * C)

    def encode_text(self, text, mask=None):
        emb = self.text_emb(text)
        emb = emb + self.text_pos_emb(jnp.arange(text.shape[1]))
        enc = self.text_transformer(emb.astype(self.cfg.dtype), mask=mask)
        enc = enc.astype(jnp.float32)
        if mask is not None:
            pooled = masked_mean(enc, mask, axis=1)
        else:
            pooled = enc.mean(axis=1)
        return l2norm(self.to_text_latent(pooled))

    def encode_image(self, image):
        emb = self.to_visual_embedding(self._patchify(image).astype(self.cfg.dtype))
        emb = emb + self.visual_pos_emb(jnp.arange(emb.shape[1]))
        enc = self.visual_transformer(emb).astype(jnp.float32)
        return l2norm(self.to_visual_latent(enc.mean(axis=1)))

    def __call__(self, text, image, text_mask=None, return_loss: bool = False):
        text_latents = self.encode_text(text, mask=text_mask)
        image_latents = self.encode_image(image)
        temp = jnp.exp(self.temperature)

        if not return_loss:
            # per-pair similarity scores (ref :278-280)
            return jnp.einsum("nd,nd->n", text_latents, image_latents,
                              preferred_element_type=jnp.float32) * temp

        sim = jnp.einsum("id,jd->ij", text_latents, image_latents,
                         preferred_element_type=jnp.float32) * temp
        b = sim.shape[0]
        labels = jnp.arange(b)
        logp_t = jax.nn.log_softmax(sim, axis=-1)
        logp_i = jax.nn.log_softmax(sim.T, axis=-1)
        ce_t = -jnp.take_along_axis(logp_t, labels[:, None], axis=1).mean()
        ce_i = -jnp.take_along_axis(logp_i, labels[:, None], axis=1).mean()
        return (ce_t + ce_i) / 2
