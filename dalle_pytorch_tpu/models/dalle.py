"""DALLE — joint text+image autoregressive transformer, TPU-native.

Capability parity with the reference `DALLE`
(`/root/reference/dalle_pytorch/dalle_pytorch.py:289-500`).  Behavioral
invariants preserved (SURVEY.md §7 checklist):

* unique padding token per text position: pad id 0 at position t is remapped
  to ``num_text_tokens + t`` where ``num_text_tokens`` was already extended
  by ``text_seq_len`` (ref :315, :440-441);
* ``<bos>`` = token 0 prepended, text pos-emb over ``text_seq_len + 1``
  (ref :320, :445);
* axial image positional embedding: summed row + column embeddings over the
  ``fmap x fmap`` raster (ref :321, external ``axial_positional_embedding``);
* logits mask forcing text positions -> text vocab, image positions -> image
  vocab (ref :356-367, :480-484); last-token drop when the sequence
  overflows (ref :473-475);
* loss = ``(loss_text + loss_img_weight * loss_img) / (loss_img_weight + 1)``
  (ref :499).

TPU-native redesign:
* the VAE is *not* a submodule: token codes are produced by the (frozen) VAE
  apply outside this module and passed in — keeping DALLE a pure function of
  (params, text, image_codes) so pjit shards it cleanly;
* generation is a jit-compiled prefill + ``lax.scan`` decode loop *with a KV
  cache* — output-equivalent to the reference's full-forward-per-token
  sampler (ref :400-415) but O(n) instead of O(n^2) per token.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..obs import prof
from ..ops.transformer import Transformer
from ..utils.helpers import max_neg_value, top_k_filter, top_p_filter


@dataclasses.dataclass(frozen=True)
class DALLEConfig:
    """Ctor-level hyperparameters (mirrors ref DALLE kwargs, dalle_pytorch.py
    :289-306) + the VAE-derived geometry the reference reads off its vae
    submodule (:310-313)."""

    dim: int
    num_text_tokens: int = 10000       # as passed in, before per-position pads
    text_seq_len: int = 256
    depth: int = 8
    heads: int = 8
    dim_head: int = 64
    reversible: bool = False
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    sparse_attn: bool = False
    attn_types: Optional[Tuple[str, ...]] = None
    loss_img_weight: int = 7
    # VAE-derived geometry (ref :310-313)
    num_image_tokens: int = 512
    image_size: int = 256
    image_fmap_size: int = 32
    # TPU-native extras
    use_remat: bool = False
    use_pallas: bool = False   # Pallas flash/block-sparse attention
    pallas_block_q: int = 128  # Pallas tile sizes (perf_ab sweeps these)
    pallas_block_k: int = 128
    logits_bf16: bool = False  # head matmul in bf16 (f32 accumulate)
    onehot_embed: bool = False  # loss-path embeds via one-hot matmul (MXU
    #                             backward instead of scatter-add); inference
    #                             forwards keep the gather
    # MoE feed-forward (model hyperparameters — they change the param tree)
    ff_experts: int = 0        # >1: MoE FF with this many experts
    ff_expert_top_k: int = 2
    ff_aux_weight: float = 0.01  # load-balance aux loss weight in training
    # dispatch mode is execution strategy over the SAME params: 'dense'
    # (every expert sees every token, exact) or 'capacity' (GShard-style
    # fixed slots, FLOPs ∝ top_k·capacity_factor instead of num_experts).
    # Plan fields (below): excluded from checkpoints, CLI-selectable per run
    ff_expert_dispatch: str = "dense"
    ff_expert_capacity_factor: float = 1.25
    # Sequence-parallel execution plan (NOT model hyperparameters: the param
    # tree and the function are identical to the dense model; these only
    # select manual collectives inside a shard_map.  Excluded from to_dict
    # so checkpoints stay topology-free.)
    ring_axis: Optional[str] = None  # mesh axis name, e.g. "sp"
    sp_impl: str = "ring"            # 'ring' | 'ulysses'
    sp_size: int = 1                 # ways of the sp axis (static shard count)
    # Training-loss head strategy: True runs one matmul per vocab phase
    # (text positions x text head, image positions x image head — skips the
    # cross-phase half of the compute, bit-identical loss).  False computes
    # both phases for every position then slices (the A/B control).  The
    # head is stored per-phase either way (PhaseLogits), so tp meshes keep
    # the sliced path: each phase kernel tp-shards on its own vocab dim.
    head_phase_sliced: bool = True
    # Decode-time cache-read strategy (ops/attention.py::decode_key_positions):
    # True gathers only the reachable keys per step, False streams the full
    # cache — the measured A/B control (tools/perf_ab.py `gen-dense`).
    sliced_kv_decode: bool = True
    # Decode-time KV-cache STORAGE dtype: True keeps the caches in bf16 even
    # when activations are f32 (checkpoint-loaded eval models default to
    # f32).  The decode loop is measured HBM-bound on cache traffic
    # (PERF.md: sliced-KV 2.16x), so halving every cache byte is a direct
    # cut to its dominant stream; attention still *accumulates* in f32
    # (ops/attention.py::decode_step computes all q·k dots with
    # preferred_element_type=f32 and softmaxes in f32), so only the stored
    # k/v values round through bf16.  False is the A/B control
    # (tools/perf_ab.py `gen_f32cache`).  No-op when dtype is already bf16.
    kv_cache_bf16: bool = True
    # Int8 cache storage (takes precedence over kv_cache_bf16): the caches
    # become (int8 values, f32 per-head scale) pairs — ops/quant.py layout
    # — halving the dominant decode byte stream AGAIN over bf16.  Scales
    # are computed once at prefill write time (per slot in the serve
    # arena); decode writes saturate under the frozen scale; every dot
    # keeps the int8 tensor as a multiplicand with f32 accumulation
    # (contract_check C2/C3 pin the no-dequant-hoist property).  OFF by
    # default until the queued `gen_int8_ab` wall-clock A/B lands.
    kv_cache_int8: bool = False
    # Int8 decode-path weights: attn/ff projection kernels + the image-
    # phase logits head are quantized ONCE per generate/serve session to
    # int8 with per-output-channel f32 scales (quantize_decode_weights)
    # and the decode program consumes ONLY the quantized copies (jit
    # prunes the unused f32 originals from its arguments) — halving the
    # weight stream that dominates small-batch decode.  Training, prefill
    # and the forward pass are untouched.
    weights_int8: bool = False
    # Serve-path sliced reads through the cache rotation as circular
    # dynamic_slice spans (<=2 per row) instead of a per-key gather —
    # bit-identical (ops/attention.py::_decode_step_aligned); False is
    # the A/B control.
    aligned_span_decode: bool = True
    # Self-speculative decoding (graftspec): a shallow-exit draft pass —
    # the first ``spec_draft_depth`` blocks + the shared logits head —
    # drafts ``spec_k - 1`` candidate tokens per decode step, then ONE
    # full-depth K-wide verify span scores all of them in a single
    # weight-stream pass.  The accepted prefix commits with the exact
    # keys/logits the greedy path would have used, so output is bitwise
    # equal to greedy whatever the acceptance rate; rejection just wastes
    # the drafted work.  Decode is HBM-bandwidth-bound (PERF.md round 5:
    # 14.9% MFU), so expected speedup = accepted-K per weight read over
    # the draft overhead (obs/prof.py::predicted_spec_speedup).  OFF by
    # default until the queued ``gen_spec_ab`` wall-clock A/B lands,
    # mirroring the int8 precedent.
    spec_decode: bool = False
    spec_draft_depth: int = 2   # draft exits after this many blocks
    spec_k: int = 4             # span width: 1 committed + up to K-1 drafted
    # test hook: score the verify span but reject every draft (m=1/step) —
    # pins the fallback path's bit-equality without relying on drafts
    # happening to miss
    spec_force_reject: bool = False
    dtype: Any = jnp.float32

    # execution-plan fields stripped from checkpoint hparams (like dtype):
    # they select how the same params are computed, not what the model is
    _PLAN_FIELDS = ("ring_axis", "sp_impl", "sp_size",
                    "ff_expert_dispatch", "ff_expert_capacity_factor",
                    "head_phase_sliced", "sliced_kv_decode", "kv_cache_bf16",
                    "kv_cache_int8", "weights_int8", "aligned_span_decode",
                    "spec_decode", "spec_draft_depth", "spec_k",
                    "spec_force_reject")

    def __post_init__(self):
        assert not (self.weights_int8 and self.ff_experts > 1), (
            "weights_int8 quantizes the dense GEGLU kernels; MoE expert "
            "kernels are not supported on the quantized decode path")
        if self.spec_decode:
            assert not self.reversible, (
                "spec_decode requires the residual executor (the reversible "
                "two-stream recurrence is sequential across positions)")
            assert 0 < self.spec_draft_depth <= self.depth, (
                f"spec_draft_depth {self.spec_draft_depth} outside "
                f"(0, depth={self.depth}]")
            assert self.spec_k >= 2, (
                f"spec_k {self.spec_k} < 2 drafts nothing; disable "
                "spec_decode instead")
            assert self.spec_k <= self.image_seq_len, (
                f"spec_k {self.spec_k} exceeds image_seq_len "
                f"{self.image_seq_len}")

    @property
    def image_seq_len(self) -> int:
        return self.image_fmap_size ** 2

    @property
    def total_text_tokens(self) -> int:
        """num_text_tokens + one unique pad id per text position (ref :315)."""
        return self.num_text_tokens + self.text_seq_len

    @property
    def seq_len(self) -> int:
        return self.text_seq_len + self.image_seq_len

    @property
    def total_tokens(self) -> int:
        return self.total_text_tokens + self.num_image_tokens

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("dtype")
        for f in self._PLAN_FIELDS:  # run topology, not model identity
            d.pop(f)
        if d.get("attn_types") is not None:
            d["attn_types"] = list(d["attn_types"])
        return d

    @classmethod
    def from_dict(cls, d: dict, **overrides) -> "DALLEConfig":
        d = {k: v for k, v in d.items()
             if k not in cls._PLAN_FIELDS}  # tolerate old ckpts carrying them
        if d.get("attn_types") is not None:
            d["attn_types"] = tuple(d["attn_types"])
        d.update(overrides)
        return cls(**d)

    @classmethod
    def from_vae(cls, vae_cfg, **kwargs) -> "DALLEConfig":
        return cls(
            num_image_tokens=vae_cfg.num_tokens,
            image_size=vae_cfg.image_size,
            image_fmap_size=vae_cfg.image_size // (2 ** vae_cfg.num_layers),
            **kwargs,
        )


class PhaseLogits(nn.Module):
    """The joint-vocab logits head, stored as one kernel PER VOCAB PHASE.

    The reference keeps a single ``nn.Linear(total_tokens)`` and masks the
    wrong-phase half to -inf afterwards (dalle_pytorch.py:482-484); here
    the text-vocab and image-vocab column blocks are separate parameters.
    Two wins over a single [dim, total] kernel with interior slicing:

    * **Phase fast paths with no slice op**: ``image_only`` multiplies only
      the image kernel (every sampled position is an image position, so the
      decode path never computes text logits), ``text_only`` mirrors it.
      A per-phase matmul is bit-identical to slicing the full product —
      each output column is an independent dot-row.
    * **Tensor parallelism**: each phase kernel is tp-sharded on ITS OWN
      vocab dim, so the phase boundary is a parameter boundary, never an
      interior slice.  A slice at ``total_text`` (7880 at CUB geometry)
      inside a single tp-sharded kernel can't align with the equal-width
      shard boundaries GSPMD requires, forcing a per-step reshard — the
      round-2 reason ``head_phase_sliced`` auto-disabled under tp.

    Joint-vocab callers get ``concat(text, image)`` — XLA folds a
    downstream phase slice of that concat back to the operand, so the
    full-logits path costs the same as before.

    Legacy single-kernel checkpoints are upgraded by
    ``utils.checkpoint.migrate_head_kernels`` (an exact column split).

    ``bf16_matmul`` runs the matmuls with bf16 inputs and f32 accumulation
    (the MXU's native mode, ~4x the f32 rate); params and the returned
    logits stay f32.
    """

    total_text: int
    total: int
    bf16_matmul: bool = False

    @nn.compact
    def __call__(self, x, image_only: bool = False, text_only: bool = False):
        assert not (image_only and text_only)
        num_image = self.total - self.total_text
        # Both phase kernels are created on EVERY call path: a module
        # initialized through a phase-only caller (e.g. prefill's
        # image_only head) must still own the full param tree, or a later
        # full-checkpoint load would find half the head missing.  Unused
        # kernels cost nothing — XLA dead-code-eliminates the untouched
        # matmul inputs from the compiled program.
        phases = {
            "text": (self.param("text_kernel", nn.initializers.lecun_normal(),
                                (x.shape[-1], self.total_text), jnp.float32),
                     self.param("text_bias", nn.initializers.zeros,
                                (self.total_text,), jnp.float32)),
            "image": (self.param("image_kernel",
                                 nn.initializers.lecun_normal(),
                                 (x.shape[-1], num_image), jnp.float32),
                      self.param("image_bias", nn.initializers.zeros,
                                 (num_image,), jnp.float32)),
        }
        wanted = []
        if not image_only:  # text phase wanted
            wanted.append("text")
        if not text_only:   # image phase wanted
            wanted.append("image")
        outs = []
        for phase in wanted:
            kernel, bias = phases[phase]
            if self.bf16_matmul:
                outs.append(jnp.dot(x.astype(jnp.bfloat16),
                                    kernel.astype(jnp.bfloat16),
                                    preferred_element_type=jnp.float32) + bias)
            else:
                outs.append(x @ kernel + bias)
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


class AxialPositionalEmbedding(nn.Module):
    """Summed per-row + per-column embeddings over the image raster
    (replaces the external ``axial_positional_embedding`` package the
    reference uses at dalle_pytorch.py:6, :321)."""

    dim: int
    fmap: int

    @nn.compact
    def __call__(self, n: int):
        row = self.param("row", nn.initializers.normal(1.0), (self.fmap, 1, self.dim))
        col = self.param("col", nn.initializers.normal(1.0), (1, self.fmap, self.dim))
        grid = (row + col).reshape(self.fmap * self.fmap, self.dim)
        return grid[:n]


def transformer_kwargs(cfg: DALLEConfig) -> dict:
    """The Transformer construction kwargs DALLE uses — exposed so the
    pipeline-parallel trainer can build the identical stage module
    (parallel/pipeline.py) without duplicating this mapping."""
    attn_types = cfg.attn_types
    if attn_types is None:
        # the reference's `sparse_attn` flag selected DeepSpeed's kernel
        # upstream (attention.py:284-342); here it selects the
        # block-sparse pattern for every layer.
        attn_types = ("sparse",) if cfg.sparse_attn else ("full",)
    return dict(
        dim=cfg.dim, depth=cfg.depth, seq_len=cfg.seq_len, causal=True,
        heads=cfg.heads, dim_head=cfg.dim_head,
        attn_dropout=cfg.attn_dropout, ff_dropout=cfg.ff_dropout,
        attn_types=tuple(attn_types), image_fmap_size=cfg.image_fmap_size,
        text_len=cfg.text_seq_len + 1, reversible=cfg.reversible,
        use_remat=cfg.use_remat, use_pallas=cfg.use_pallas,
        pallas_block_q=cfg.pallas_block_q,
        pallas_block_k=cfg.pallas_block_k,
        ring_axis=cfg.ring_axis, sp_impl=cfg.sp_impl,
        sliced_kv_decode=cfg.sliced_kv_decode,
        aligned_span_decode=cfg.aligned_span_decode,
        ff_experts=cfg.ff_experts, ff_expert_top_k=cfg.ff_expert_top_k,
        ff_expert_dispatch=cfg.ff_expert_dispatch,
        ff_expert_capacity_factor=cfg.ff_expert_capacity_factor,
        dtype=cfg.dtype)


class DALLE(nn.Module):
    cfg: DALLEConfig

    def setup(self):
        cfg = self.cfg
        self.text_emb = nn.Embed(cfg.total_text_tokens, cfg.dim,
                                 embedding_init=nn.initializers.normal(1.0),
                                 name="text_emb")
        self.image_emb = nn.Embed(cfg.num_image_tokens, cfg.dim,
                                  embedding_init=nn.initializers.normal(1.0),
                                  name="image_emb")
        self.text_pos_emb = nn.Embed(cfg.text_seq_len + 1, cfg.dim,
                                     embedding_init=nn.initializers.normal(1.0),
                                     name="text_pos_emb")
        self.image_pos_emb = AxialPositionalEmbedding(
            cfg.dim, cfg.image_fmap_size, name="image_pos_emb")
        self.transformer = Transformer(name="transformer",
                                       **transformer_kwargs(cfg))
        self.final_norm = nn.LayerNorm(dtype=jnp.float32, name="final_norm")
        self.to_logits_dense = PhaseLogits(cfg.total_text_tokens,
                                           cfg.total_tokens,
                                           bf16_matmul=cfg.logits_bf16,
                                           name="to_logits_dense")

    # --- embedding helpers ---

    def _remap_pad_tokens(self, text):
        """Pad id 0 at text position t -> unique id num_text_tokens + t
        (ref :315, :440-441)."""
        cfg = self.cfg
        text_range = jnp.arange(cfg.text_seq_len) + (
            cfg.total_text_tokens - cfg.text_seq_len)
        return jnp.where(text == 0, text_range, text)

    def _lookup(self, table: nn.Embed, ids, onehot: bool):
        """Token lookup; with ``onehot`` the gather becomes a one-hot matmul
        whose transpose (the embedding gradient) is a plain matmul on the
        MXU instead of a scatter-add.  HIGHEST precision keeps the forward
        bit-exact with the gather — TPU's default f32 matmul precision would
        round the selected rows through bf16."""
        if onehot:
            oh = jax.nn.one_hot(ids, table.num_embeddings,
                                dtype=table.embedding.dtype)
            # graftlint: disable=DOT001 (uniform: oh is built in the table dtype; HIGHEST precision pins the f32-exact product)
            return jnp.dot(oh, table.embedding,
                           precision=jax.lax.Precision.HIGHEST)
        return table(ids)

    def _embed_text(self, text, onehot: bool = False):
        """Unique-pad remap + <bos> + token/pos embeddings (ref :440-448)."""
        cfg = self.cfg
        assert text.shape[-1] == cfg.text_seq_len, (
            f"text length {text.shape[-1]} != text_seq_len {cfg.text_seq_len}"
        )
        text = jnp.pad(self._remap_pad_tokens(text), ((0, 0), (1, 0)))  # <bos> id 0
        tokens = self._lookup(self.text_emb, text, onehot)
        tokens = tokens + self.text_pos_emb(jnp.arange(text.shape[1]))
        return tokens.astype(cfg.dtype)

    def _embed_image_codes(self, codes, onehot: bool = False):
        emb = self._lookup(self.image_emb, codes, onehot)
        emb = emb + self.image_pos_emb(codes.shape[1])
        return emb.astype(self.cfg.dtype)

    @staticmethod
    def _pad_mask_for_bos(mask):
        """Text key-pad mask [b, text_seq_len] -> [b, text_seq_len+1]: after
        <bos> is prepended, mask bit t governs key position t+1; <bos> itself
        is always attendable.  (The reference accepts a mask but drops it in
        forward — `out = self.transformer(tokens)` at dalle_pytorch.py:477;
        we keep the parameter and make it actually correct.)"""
        if mask is None:
            return None
        return jnp.pad(mask, ((0, 0), (1, 0)), constant_values=True)

    def _logits_mask(self, n: int):
        """[n, total_tokens] — True where the logit must be suppressed
        (ref :356-367)."""
        cfg = self.cfg
        seq_range = jnp.arange(n)[:, None]
        logits_range = jnp.arange(cfg.total_tokens)[None, :]
        return (
            ((seq_range >= cfg.text_seq_len) & (logits_range < cfg.total_text_tokens))
            | ((seq_range < cfg.text_seq_len) & (logits_range >= cfg.total_text_tokens))
        )

    # --- main forward (ref :428-500) ---

    def embed_sequence(self, text, image_codes=None, onehot: bool = False):
        """[bos+text | image] token embeddings, truncated to seq_len (ref
        :440-475) — the input to the transformer stack.  Exposed as a
        method so the pipeline-parallel trainer can run embeddings outside
        the pipelined stack (training.py::make_dalle_pp_train_step)."""
        cfg = self.cfg
        with prof.scope("embed"):
            tokens = self._embed_text(text, onehot)
            if image_codes is not None and image_codes.shape[1] > 0:
                image_emb = self._embed_image_codes(image_codes, onehot)
                tokens = jnp.concatenate([tokens, image_emb], axis=1)
            # drop the final token when the sequence overflows (ref :473-475)
            if tokens.shape[1] > cfg.seq_len:
                tokens = tokens[:, : cfg.seq_len]
            return tokens

    def _head(self, out, image_only: bool = False, text_only: bool = False,
              qhead=None):
        """final-norm (f32) + logits head — shared by the dense loss, the
        sp loss, the inference forward and the prefill/decode paths.
        ``qhead`` (decode only, ``weights_int8``) is the session-quantized
        image-phase kernel ``(int8, scale, bias)``: the head matmul then
        runs the int8 kernel as a direct multiplicand (f32 accumulation),
        bypassing — and letting jit prune — the f32 PhaseLogits params."""
        with prof.scope("logits-head"):
            h = self.final_norm(out.astype(jnp.float32))
            if qhead is not None:
                assert image_only, "quantized head is the decode (image) phase"
                from ..ops.quant import qdense
                return qdense(h, *qhead)  # f32 logits
            return self.to_logits_dense(h, image_only=image_only,
                                        text_only=text_only)

    @staticmethod
    def _phase_nll(phase_logits, labels):
        """Per-position negative log-likelihood within one vocab phase."""
        lse = jax.nn.logsumexp(phase_logits, axis=-1)
        ll = jnp.take_along_axis(
            phase_logits, labels[:, :, None], axis=-1)[..., 0]
        return lse - ll

    def loss_from_hidden(self, out, text, image_codes):
        """final-norm + logits head + phase-sliced CE over full-sequence
        transformer output ``out`` [b, n, d] (the second half of the dense
        training forward; also the pipeline trainer's exit path)."""
        cfg = self.cfg
        # Phase-sliced cross-entropy AND head: text positions multiply only
        # the text-vocab kernel columns, image positions only the image-vocab
        # columns, and each phase normalizes within its own vocab.  Identical
        # to the reference's full-head + masked-logits softmax (ref :482-499
        # — masked entries are -inf and vanish from the logsumexp; and a
        # column-sliced dot is bit-identical to slicing the full product)
        # but never materializes the [b, n, total_tokens] logits/logprobs/
        # mask tensors, and skips the cross-phase half of the head matmul:
        # at the CUB geometry that is ~2 x 1.1 GB less HBM traffic and ~9%
        # fewer step FLOPs (utils/profiling.py::dalle_train_flops counts
        # this sliced head).
        T = cfg.text_seq_len
        # labels: next-token over [text[1:], image codes] (ref :489-499)
        if cfg.head_phase_sliced:
            text_logits = self._head(out[:, :T], text_only=True)
            img_logits = self._head(out[:, T:], image_only=True)
        else:  # full head then slice — for tp meshes (see DALLEConfig)
            logits = self._head(out)
            V_text = cfg.total_text_tokens
            text_logits = logits[:, :T, :V_text]
            img_logits = logits[:, T:, V_text:]
        with prof.scope("logits-head"):
            loss_text = self._phase_nll(text_logits,
                                        self._remap_pad_tokens(text)).mean()
            loss_img = self._phase_nll(img_logits, image_codes).mean()
            return (loss_text + cfg.loss_img_weight * loss_img) / (cfg.loss_img_weight + 1)

    def _sp_loss(self, text, image_codes, onehot: bool, deterministic: bool):
        """Sequence-parallel training loss — runs INSIDE a shard_map over
        ``cfg.ring_axis`` (training.py::make_dalle_sp_train_step).

        Embeddings are computed on the full sequence (cheap: gathers + adds)
        and the local shard sliced off; the transformer — where the FLOPs
        are — sees only ``seq_len / sp_size`` positions per device, with
        ring/Ulysses collectives making attention exact.  The phase CE is
        computed per local position against its *global* phase and label,
        then psum'd, reproducing the dense loss exactly.
        """
        cfg = self.cfg
        S = cfg.sp_size
        tokens = self.embed_sequence(text, image_codes, onehot)
        n = tokens.shape[1]
        assert n % S == 0, f"seq_len {n} not divisible by sp_size {S}"
        L = n // S
        idx = jax.lax.axis_index(cfg.ring_axis)
        x = jax.lax.dynamic_slice_in_dim(tokens, idx * L, L, axis=1)

        out = self.transformer(x, deterministic=deterministic)
        logits = self._head(out)               # [b, L, total_tokens]

        T, V_text = cfg.text_seq_len, cfg.total_text_tokens
        pos = idx * L + jnp.arange(L)          # global positions of my shard
        is_text = pos < T
        text_labels = self._remap_pad_tokens(text)
        lab_t = jnp.take(text_labels, jnp.clip(pos, 0, T - 1), axis=1)
        lab_i = jnp.take(image_codes,
                         jnp.clip(pos - T, 0, image_codes.shape[1] - 1), axis=1)

        def phase_ce_sum(phase_logits, labels, sel):
            return jnp.where(sel[None, :],
                             self._phase_nll(phase_logits, labels), 0.0).sum()

        b = text.shape[0]
        with prof.scope("logits-head"):
            sum_t = jax.lax.psum(
                phase_ce_sum(logits[..., :V_text], lab_t, is_text),
                cfg.ring_axis)
            sum_i = jax.lax.psum(
                phase_ce_sum(logits[..., V_text:], lab_i, ~is_text),
                cfg.ring_axis)
            loss_text = sum_t / (b * T)
            loss_img = sum_i / (b * cfg.image_seq_len)
            return (loss_text + cfg.loss_img_weight * loss_img) / (cfg.loss_img_weight + 1)

    def __call__(self, text, image_codes=None, mask=None, return_loss: bool = False,
                 deterministic: bool = True):
        cfg = self.cfg
        # one-hot embeds only pay off through their backward — inference
        # forwards (return_loss=False, prefill, decode) keep the gather
        onehot = cfg.onehot_embed and return_loss

        if return_loss and cfg.ring_axis is not None and cfg.sp_size > 1 \
                and not self.is_initializing():
            assert image_codes is not None, (
                "when training, image codes must be supplied")
            assert mask is None, (
                "sequence-parallel training does not take a key padding mask")
            return self._sp_loss(text, image_codes, onehot, deterministic)

        tokens = self.embed_sequence(text, image_codes, onehot)
        n = tokens.shape[1]

        out = self.transformer(tokens, mask=self._pad_mask_for_bos(mask),
                               deterministic=deterministic)

        if not return_loss:
            logits = self._head(out)
            return jnp.where(self._logits_mask(n)[None],
                             max_neg_value(logits.dtype), logits)

        assert image_codes is not None, "when training, image codes must be supplied"
        return self.loss_from_hidden(out, text, image_codes)

    # --- generation (prefill + decode; ref generate_images :370-426) ---

    def prefill(self, text, prime_codes=None, mask=None):
        """Run the forward over [bos+text (+ primed image codes)], padded to
        the full static seq_len, returning (last-position image-phase
        logits [b, num_image_tokens], caches)."""
        cfg = self.cfg
        with prof.scope("embed"):
            tokens = self._embed_text(text)
            n_pre = tokens.shape[1]
            if prime_codes is not None and prime_codes.shape[1] > 0:
                tokens = jnp.concatenate(
                    [tokens, self._embed_image_codes(prime_codes)], axis=1)
                n_pre = tokens.shape[1]
            pad = cfg.seq_len - tokens.shape[1]
            assert pad >= 0, ("priming must leave at least one image token "
                              "to sample")
            tokens = jnp.pad(tokens, ((0, 0), (0, pad), (0, 0)))

        out, kvs = self.transformer(tokens, mask=self._pad_mask_for_bos(mask),
                                    return_kv=True)
        if cfg.kv_cache_int8:
            # int8 cache storage: per-head symmetric scales computed HERE,
            # at prefill write time — the one place the whole sequence is
            # in hand — then frozen for the decode writes (ops/quant.py
            # scale-layout contract).  Takes precedence over kv_cache_bf16.
            from ..ops.quant import quantize_per_head
            with prof.scope("attn-cache"):
                kvs = [(quantize_per_head(k), quantize_per_head(v))
                       for k, v in kvs]
        elif cfg.kv_cache_bf16:
            # cache STORAGE dtype only: the decode step re-reads these
            # through f32-accumulating dots (ops/attention.py::decode_step),
            # so this is a pure byte cut on the HBM-bound decode loop
            with prof.scope("attn-cache"):
                kvs = [(k.astype(jnp.bfloat16), v.astype(jnp.bfloat16))
                       for k, v in kvs]
        last = out[:, n_pre - 1 : n_pre]
        logits = self._head(last, image_only=True)
        return logits[:, 0], kvs

    def decode_step(self, code, caches, index, mask=None, write_pos=None,
                    qweights=None):
        """One sampled image code in, next-position logits out.

        `code` [b] is the image-vocab token at *input* position `index`
        (traced); returns ([b, num_image_tokens] image-phase logits, new
        caches) — text logits would be -inf here (ref mask :482-484) and
        are never computed.

        With ``write_pos`` (the serving arena's phase-aligned mode, see
        ops/attention.py), ``index`` may be a per-row [b] vector — every
        row decodes at its own depth against rotated caches that all
        write the same physical column.

        ``qweights`` (``weights_int8``) is the session-quantized weight
        tree from :func:`quantize_decode_weights`; the attention/FF
        projections and the image head then run int8 multiplicands with
        f32 accumulation instead of streaming the f32 params."""
        cfg = self.cfg
        with prof.scope("decode-step"):
            with prof.scope("embed"):
                emb = self.image_emb(code[:, None])
                img_index = index - (cfg.text_seq_len + 1)
                pos_grid = self.image_pos_emb(cfg.image_seq_len)
                if jnp.ndim(index) > 0:
                    # per-row positions: gather each row's pos-emb (clipped
                    # like dynamic_slice clamps — idle serve slots park out
                    # of range)
                    rows = jnp.clip(img_index, 0, cfg.image_seq_len - 1)
                    emb = emb + jnp.take(pos_grid, rows, axis=0)[:, None]
                else:
                    emb = emb + jax.lax.dynamic_slice_in_dim(
                        pos_grid, img_index, 1, axis=0)[None]
                x = emb.astype(cfg.dtype)
            out, caches = self.transformer.decode_step(
                x, caches, index, mask=self._pad_mask_for_bos(mask),
                write_pos=write_pos,
                qweights=None if qweights is None else qweights["layers"])
            logits = self._head(out, image_only=True,
                                qhead=None if qweights is None
                                else qweights["head"])
            return logits[:, 0], caches

    def decode_span(self, codes, caches, qpos, rot, valid, depth_limit=None,
                    qweights=None):
        """K-token speculative span: ``codes`` [b, K] image-vocab tokens at
        logical input positions ``qpos`` [b, K] (consecutive per row),
        per-row cache rotation ``rot`` [b] (zeros for the static sampler),
        cache-write validity ``valid`` [b, K].  Returns ([b, K,
        num_image_tokens] image-phase logits — position j's logits predict
        the token AFTER ``qpos[:, j]`` — and the updated caches).

        ``depth_limit`` (static int) is the self-speculative draft's
        shallow exit: only the first that many blocks run, then the SAME
        final-norm + image head scores the truncated hidden state.  The
        verify pass (depth_limit=None) is the full model and its logits
        are bitwise what ``decode_step`` would produce query-by-query —
        the property the spec-decode commit relies on."""
        cfg = self.cfg
        with prof.scope("decode-step"):
            with prof.scope("embed"):
                emb = self.image_emb(codes)               # [b, K, dim]
                img_index = qpos - (cfg.text_seq_len + 1)
                pos_grid = self.image_pos_emb(cfg.image_seq_len)
                rows = jnp.clip(img_index, 0, cfg.image_seq_len - 1)
                x = (emb + jnp.take(pos_grid, rows, axis=0)).astype(cfg.dtype)
            out, caches = self.transformer.decode_span(
                x, caches, qpos, rot, valid, depth_limit=depth_limit,
                qweights=None if qweights is None else qweights["layers"])
            logits = self._head(out, image_only=True,
                                qhead=None if qweights is None
                                else qweights["head"])
            return logits, caches


def quantize_decode_weights(params, cfg: DALLEConfig):
    """One-shot int8 quantization of every decode-path weight matrix —
    the ``weights_int8`` half of the quantized-serving recipe.

    Run ONCE per generate/serve session (the serve arena does it at
    construction; ``decode_codes`` does it per jitted call, where XLA
    hoists it out of the decode scan): returns the quantized-weight tree
    ``DALLE.decode_step`` consumes — per layer ``{"qkv": (int8 [dim, 3,
    h, dh], f32 scale), "out"/"ff_in"/"ff_out": (int8, scale, f32
    bias)}`` plus ``"head"`` for the image-phase logits kernel.  Scales
    are per-output-channel (ops/quant.py::quantize_weight, reduced over
    the input dim), so every output column keeps its own dynamic range —
    the LLM.int8() weight layout.  The f32 originals stay in ``params``
    untouched (checkpoints, training and prefill never see int8); the
    compiled decode/tick programs simply stop referencing them, so jit's
    unused-argument pruning removes them from the weight stream."""
    from ..ops.quant import quantize_weight

    assert cfg.ff_experts <= 1, (
        "weights_int8 does not cover MoE expert kernels")
    if "params" in params:  # accept the full variables dict too
        params = params["params"]
    t = params["transformer"]
    layers = []
    for i in range(cfg.depth):
        attn = t[f"layers_{i}_attn"]["attn"]
        ff = t[f"layers_{i}_ff"]
        layers.append({
            "qkv": quantize_weight(attn["to_qkv"]["kernel"]),
            "out": (*quantize_weight(attn["to_out"]["kernel"]),
                    attn["to_out"]["bias"]),
            "ff_in": (*quantize_weight(ff["dense_in"]["kernel"]),
                      ff["dense_in"]["bias"]),
            "ff_out": (*quantize_weight(ff["dense_out"]["kernel"]),
                       ff["dense_out"]["bias"]),
        })
    head = params["to_logits_dense"]
    return {"layers": layers,
            "head": (*quantize_weight(head["image_kernel"]),
                     head["image_bias"])}


def sample_image_code(logits, key, *, k_vocab: int,
                      filter_thres: float = 0.5, temperature=1.0,
                      top_p: Optional[float] = None) -> jax.Array:
    """Sample image codes from image-phase logits ``[..., num_image_tokens]``.

    THE sampling semantics of this repo, shared by ``decode_codes`` and the
    serving tick (``serve/engine.py``) so the two paths cannot drift:
    logits are image-vocab-only, ``k`` still derives from the full joint
    vocab (reference semantics — its text entries were -inf and could never
    win a slot), and the sampled index IS the image code (the reference's
    ``- num_text_tokens`` offset is pre-applied by slicing).  Temperature
    scales BEFORE the filters: top-k is invariant to the monotone rescale
    (so reference top-k semantics are untouched) but the nucleus must be
    the p-mass set of the distribution actually sampled.  ``temperature``
    may be a traced scalar/array (the serve path carries it per request),
    ``filter_thres``/``top_p`` stay static (``top_k_filter`` derives a
    static k)."""
    logits = logits / temperature
    filtered = top_k_filter(logits, thres=filter_thres, k_vocab=k_vocab)
    if top_p is not None:
        filtered = top_p_filter(filtered, top_p)
    return jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)


def prefill_codes(dalle: DALLE, params, text, *, prime_codes=None,
                  mask=None):
    """The prompt half of the sampler: run the full forward over
    [bos+text (+prime)] once, returning ``(first_logits [b, num_image_
    tokens], caches)`` — the state ``decode_codes`` continues from.

    Split out of ``generate_codes`` so callers sampling MANY candidates of
    the SAME prompt (cli.generate_chunked, genrank) can pay this forward
    once per unique prompt and ``tile_prefill`` the result across the
    candidate batch, instead of re-running the prefill transformer for
    every batch-size chunk."""
    return dalle.apply(params, text, prime_codes, mask, method=DALLE.prefill)


def broadcast_prefill(first_logits, caches, reps: int):
    """Tile a prefill state across ``reps`` batch rows — THE shared
    broadcast primitive behind every prompt-reuse path (``tile_prefill``
    for same-prompt candidate batches, ``serve/prefix.py`` for radix
    prefix-cache re-admissions), so the rotation/tiling logic lives in
    exactly one place."""
    if reps == 1:
        return first_logits, caches
    rep = lambda a: jnp.repeat(a, reps, axis=0)  # noqa: E731
    # tree_map, not tuple unpacking: int8 cache entries are (values,
    # scale) pairs and the per-head scale planes tile on the same axis
    return rep(first_logits), jax.tree.map(rep, caches)


def tile_prefill(first_logits, caches, reps: int):
    """Broadcast a batch-1 prefill state across ``reps`` candidates.

    Every candidate of one prompt shares an identical prefill (the prompt
    positions' k/v never depend on the sampled continuation), so tiling the
    cached state is exact — one HBM write of the caches instead of ``reps``
    prefill forwards.  The per-candidate divergence comes entirely from the
    decode loop's rng."""
    assert first_logits.shape[0] == 1, (
        "tile_prefill broadcasts a single-prompt (batch-1) prefill; "
        f"expected first_logits batch shape (1, ...), got shape "
        f"{tuple(first_logits.shape)}")
    return broadcast_prefill(first_logits, caches, reps)


def decode_codes(dalle: DALLE, params, first_logits, caches, rng, *,
                 n_prime: int = 0, prime_codes=None,
                 filter_thres: float = 0.5, temperature: float = 1.0,
                 top_p: Optional[float] = None, mask=None) -> jax.Array:
    """The sampling half: `lax.scan` KV-cache decode from a prefill state
    (``prefill_codes`` or a ``tile_prefill`` broadcast of one).  Sampling
    semantics match the reference exactly (top_k filter with
    ``k = max(int((1-thres)*vocab), 1)``, temperature softmax, categorical
    draw, image-vocab offset subtraction; ref dalle_pytorch.py:400-415).
    ``top_p`` additionally applies nucleus filtering after top-k (a knob
    the reference lacks).
    """
    cfg = dalle.cfg
    n_pre = cfg.text_seq_len + 1 + n_prime

    def sample(logits, key):
        return sample_image_code(logits, key, k_vocab=cfg.total_tokens,
                                 filter_thres=filter_thres,
                                 temperature=temperature, top_p=top_p)

    if cfg.spec_decode:
        assert n_prime == 0 and prime_codes is None, (
            "spec_decode does not support primed image codes; prime on the "
            "greedy sampler or disable spec_decode")
        assert mask is None, (
            "spec_decode's span path takes no key padding mask (serve "
            "precedent: requests carry fully-valid prompts)")
        return _decode_codes_spec(dalle, params, first_logits, caches, rng,
                                  sample=sample)

    def step(carry, key):
        code, caches, index = carry
        logits, caches = dalle.apply(
            params, code, caches, index, mask, None, qweights,
            method=DALLE.decode_step)
        next_code = sample(logits, key)
        return (next_code, caches, index + 1), next_code

    with prof.scope("decode-step"):
        # weights_int8: quantize once per call — a scan constant, so XLA
        # hoists it and the decode loop streams only the int8 copies
        qweights = (quantize_decode_weights(params, cfg)
                    if cfg.weights_int8 else None)
        rng, key0 = jax.random.split(rng)
        first_code = sample(first_logits, key0)

        num_steps = cfg.seq_len - n_pre  # remaining image positions
        keys = (jax.random.split(rng, num_steps) if num_steps > 0
                else jnp.zeros((0, 2), jnp.uint32))
        (_, _, _), rest = jax.lax.scan(
            step, (first_code, caches, jnp.asarray(n_pre)), keys)
        rest = rest.transpose(1, 0)  # [b, num_steps]

        parts = [first_code[:, None], rest]
        if prime_codes is not None and n_prime > 0:
            parts.insert(0, prime_codes)
        return jnp.concatenate(parts, axis=1)


def _decode_codes_spec(dalle: DALLE, params, first_logits, caches, rng, *,
                       sample) -> jax.Array:
    """The ``spec_decode`` branch of :func:`decode_codes`: a
    ``lax.while_loop`` that drafts ``spec_k - 1`` tokens through the
    shallow-exit stack, scores all ``spec_k`` span positions in one
    full-depth verify pass, and commits the accepted prefix — rows
    advance by their own accepted length per iteration, so the loop is
    while-not-done rather than a fixed-length scan.

    Exactness: commit j is sampled from the FULL-model verify logits with
    the same key stream position the greedy scan would have used, and a
    draft is only accepted when it equals that commit — so the committed
    sequence is bitwise the greedy sequence whatever the drafts guessed.
    (At batch > 1, diverged rows draw through a per-row vmapped sampler
    instead of the greedy scan's one-key-per-step batched draw — at
    batch 1, and under argmax sampling at any batch, the two are
    identical.)  Rejected span positions leave junk k/v in the caches at
    positions >= the new index: causally masked until the next
    iteration's span overwrites them (it always covers them — the span
    starts at the new index and is as wide as the old one)."""
    cfg = dalle.cfg
    n_pre = cfg.text_seq_len + 1
    L = cfg.image_seq_len
    K = cfg.spec_k
    b = first_logits.shape[0]
    num_steps = cfg.seq_len - n_pre  # L - 1 keys, one per later position
    sample_rows = jax.vmap(sample)   # per-row key (rows diverge in pos)

    with prof.scope("decode-step"):
        qweights = (quantize_decode_weights(params, cfg)
                    if cfg.weights_int8 else None)
        rng, key0 = jax.random.split(rng)
        first_code = sample(first_logits, key0)
        keys_all = (jax.random.split(rng, num_steps) if num_steps > 0
                    else jnp.zeros((1, 2), jnp.uint32))
        rot0 = jnp.zeros((b,), jnp.int32)  # static sampler: unrotated caches

        def body(carry):
            caches, code, pos, out = carry
            active = pos < L
            remaining = L - pos
            index = n_pre + pos - 1  # input position of the last committed
            # keys for out positions pos..pos+K-1 (position p draws
            # keys_all[p-1], matching the greedy scan's stream)
            kspan = jax.vmap(lambda p: jnp.take(
                keys_all, jnp.clip(p - 1 + jnp.arange(K), 0,
                                   keys_all.shape[0] - 1), axis=0))(pos)
            drafts = []
            d = code
            with prof.scope("spec-draft"):
                for j in range(1, K):
                    qp = (index + (j - 1))[:, None]
                    dvalid = (active & (j - 1 < remaining))[:, None]
                    dlogits, caches = dalle.apply(
                        params, d[:, None], caches, qp, rot0, dvalid,
                        cfg.spec_draft_depth, qweights,
                        method=DALLE.decode_span)
                    d = sample_rows(dlogits[:, 0], kspan[:, j - 1])
                    drafts.append(d)
            t = jnp.stack([code] + drafts, axis=1)        # [b, K]
            qpos = index[:, None] + jnp.arange(K)[None, :]
            vvalid = active[:, None] & (jnp.arange(K)[None, :]
                                        < remaining[:, None])
            with prof.scope("spec-verify"):
                vlogits, caches = dalle.apply(
                    params, t, caches, qpos, rot0, vvalid, None, qweights,
                    method=DALLE.decode_span)
            cand = jax.vmap(sample_rows, in_axes=1, out_axes=1)(
                vlogits, kspan)                           # [b, K]
            if cfg.spec_force_reject:
                matches = jnp.zeros((b,), jnp.int32)
            else:
                eq = (t[:, 1:] == cand[:, :-1]).astype(jnp.int32)
                matches = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)
            m = jnp.where(active,
                          jnp.minimum(matches + 1, jnp.maximum(remaining, 1)),
                          0)
            last = jnp.take_along_axis(
                cand, jnp.clip(m - 1, 0, K - 1)[:, None], axis=1)[:, 0]

            def write_row(row, p, c, mm):
                jj = jnp.arange(K)
                idxs = jnp.where(jj < mm, p + jj, L)  # L = dropped lane
                return row.at[idxs].set(c, mode="drop")

            out = jax.vmap(write_row)(out, pos, cand, m)
            return (caches, jnp.where(active, last, code), pos + m, out)

        out0 = jnp.zeros((b, L), jnp.int32).at[:, 0].set(first_code)
        _, _, _, out = jax.lax.while_loop(
            lambda c: jnp.any(c[2] < L), body,
            (caches, first_code, jnp.ones((b,), jnp.int32), out0))
        return out


def generate_codes(dalle: DALLE, params, text, rng, *, prime_codes=None,
                   filter_thres: float = 0.5, temperature: float = 1.0,
                   top_p: Optional[float] = None, mask=None) -> jax.Array:
    """Sample a full image token sequence [b, image_seq_len].

    Pure jittable function: ``prefill_codes`` once, then the
    ``decode_codes`` scan — the one-shot composition of the split halves
    (callers amortizing one prompt across many candidates use the halves
    directly; see ``tile_prefill``)."""
    n_prime = 0 if prime_codes is None else prime_codes.shape[1]
    first_logits, caches = prefill_codes(dalle, params, text,
                                         prime_codes=prime_codes, mask=mask)
    return decode_codes(dalle, params, first_logits, caches, rng,
                        n_prime=n_prime, prime_codes=prime_codes,
                        filter_thres=filter_thres, temperature=temperature,
                        top_p=top_p, mask=mask)
