"""Pretrained VAE wrappers: OpenAI discrete VAE and Taming VQGAN, in JAX.

Capability parity with `/root/reference/dalle_pytorch/vae.py`:

* ``OpenAIDiscreteVAE`` — OpenAI's dVAE (8192 tokens, f=8 i.e. num_layers=3,
  256px), ref vae.py:98-127.  The reference downloads pickled torch modules;
  here the graph is a native JAX conv stack and the weights are *converted*
  from the torch checkpoint (`convert_openai_weights`).
* ``VQGanVAE1024`` — Heidelberg taming-transformers VQGAN (1024 codes, f=16
  i.e. num_layers=4, 256px), ref vae.py:132-170, with the codebook
  nearest-neighbor quantization on encode and the [-1,1]->[0,1] clamp on
  decode (ref :154-170).
* rank-coordinated download barrier semantics (ref vae.py:53-94): only the
  local-root process materializes weights; peers wait on the backend barrier.

This environment has no network egress, so the actual pretrained weights
cannot be fetched here; construction requires a local converted-weights file
(``weights_path``).  The model *graphs* are complete and unit-tested with
random weights; `convert_torch_state_dict` maps a torch state_dict onto them.

Both classes expose the duck-typed interface DALLE needs (ref
dalle_pytorch.py:308-313): ``image_size``, ``num_layers``, ``num_tokens``,
``get_codebook_indices(img)``, ``decode(img_seq)``.
"""
from __future__ import annotations

import dataclasses
import math
from pathlib import Path
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def map_pixels(x, eps: float = 0.1):
    """OpenAI dVAE input squash (ref vae.py:47-51)."""
    return (1 - 2 * eps) * x + eps


def unmap_pixels(x, eps: float = 0.1):
    return jnp.clip((x - eps) / (1 - 2 * eps), 0.0, 1.0)


# ---------------------------------------------------------------------------
# OpenAI dVAE graph (mirrors the published DALL-E encoder/decoder topology:
# conv stem, 4 groups of residual bottleneck blocks with maxpool/upsample)
# ---------------------------------------------------------------------------


class _EncBlock(nn.Module):
    n_out: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.relu(x)
        h = nn.Conv(self.n_out // 4, (3, 3), padding=1, dtype=self.dtype, name="conv_1")(h)
        h = nn.relu(h)
        h = nn.Conv(self.n_out // 4, (3, 3), padding=1, dtype=self.dtype, name="conv_2")(h)
        h = nn.relu(h)
        h = nn.Conv(self.n_out // 4, (3, 3), padding=1, dtype=self.dtype, name="conv_3")(h)
        h = nn.relu(h)
        h = nn.Conv(self.n_out, (1, 1), dtype=self.dtype, name="conv_4")(h)
        if x.shape[-1] != self.n_out:
            x = nn.Conv(self.n_out, (1, 1), dtype=self.dtype, name="id_path")(x)
        return x + h


class OpenAIEncoder(nn.Module):
    num_tokens: int = 8192
    hidden: int = 256
    blocks_per_group: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.hidden, (7, 7), padding=3, dtype=self.dtype, name="stem")(x)
        for g, mult in enumerate((1, 2, 4, 8)):
            for b in range(self.blocks_per_group):
                h = _EncBlock(self.hidden * mult, dtype=self.dtype,
                              name=f"group_{g}_block_{b}")(h)
            if g < 3:
                h = nn.max_pool(h, (2, 2), strides=(2, 2))
        h = nn.relu(h)
        return nn.Conv(self.num_tokens, (1, 1), dtype=jnp.float32, name="head")(h)


class _DecBlock(nn.Module):
    n_out: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.relu(x)
        h = nn.Conv(self.n_out // 4, (1, 1), dtype=self.dtype, name="conv_1")(h)
        h = nn.relu(h)
        h = nn.Conv(self.n_out // 4, (3, 3), padding=1, dtype=self.dtype, name="conv_2")(h)
        h = nn.relu(h)
        h = nn.Conv(self.n_out // 4, (3, 3), padding=1, dtype=self.dtype, name="conv_3")(h)
        h = nn.relu(h)
        h = nn.Conv(self.n_out, (3, 3), padding=1, dtype=self.dtype, name="conv_4")(h)
        if x.shape[-1] != self.n_out:
            x = nn.Conv(self.n_out, (1, 1), dtype=self.dtype, name="id_path")(x)
        return x + h


class OpenAIDecoder(nn.Module):
    num_tokens: int = 8192
    hidden: int = 256
    blocks_per_group: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, codes_onehot_or_emb):
        # published dVAE decoder: 1x1 stem to n_init = hidden//2 (128), then
        # groups of width hidden*mult (2048/1024/512/256) — the first block
        # expands n_init -> 8*hidden via its id_path
        h = nn.Conv(self.hidden // 2, (1, 1), dtype=self.dtype, name="stem")(
            codes_onehot_or_emb)
        for g, mult in enumerate((8, 4, 2, 1)):
            for b in range(self.blocks_per_group):
                h = _DecBlock(self.hidden * mult, dtype=self.dtype,
                              name=f"group_{g}_block_{b}")(h)
            if g < 3:
                b_, hh, ww, cc = h.shape
                h = jax.image.resize(h, (b_, hh * 2, ww * 2, cc), "nearest")
        h = nn.relu(h)
        return nn.Conv(6, (1, 1), dtype=jnp.float32, name="head")(h)  # mean+logvar RGB


@dataclasses.dataclass
class OpenAIDiscreteVAE:
    """Inference-only wrapper (ref vae.py:98-127)."""

    weights_path: Optional[str] = None
    image_size: int = 256
    num_layers: int = 3       # f = 8 (ref vae.py:110)
    num_tokens: int = 8192
    dtype: Any = jnp.float32

    def __post_init__(self):
        self.encoder = OpenAIEncoder(num_tokens=self.num_tokens, dtype=self.dtype)
        self.decoder = OpenAIDecoder(num_tokens=self.num_tokens, dtype=self.dtype)
        self.params = None
        if self.weights_path is not None:
            from ..utils.checkpoint import load_checkpoint

            self.params = load_checkpoint(self.weights_path)

    def init_random(self, rng):
        """Random-weight init (graph testing without the released weights)."""
        f = self.image_size // (2 ** self.num_layers)
        enc = self.encoder.init(rng, jnp.zeros((1, self.image_size, self.image_size, 3)))
        dec = self.decoder.init(rng, jnp.zeros((1, f, f, self.num_tokens)))
        self.params = {"encoder": enc["params"], "decoder": dec["params"]}
        return self.params

    def _require_params(self):
        if self.params is None:
            raise RuntimeError(
                "OpenAIDiscreteVAE needs converted weights. This environment "
                "has no network egress; run convert_openai_weights() on the "
                "released torch checkpoints and pass weights_path=..., or use "
                "init_random() for graph testing."
            )

    def get_codebook_indices(self, img):
        self._require_params()
        logits = self.encoder.apply({"params": self.params["encoder"]},
                                    map_pixels(img))
        b = logits.shape[0]
        return jnp.argmax(logits, axis=-1).reshape(b, -1).astype(jnp.int32)

    def decode(self, img_seq):
        self._require_params()
        b, n = img_seq.shape
        f = int(math.isqrt(n))
        onehot = jax.nn.one_hot(img_seq, self.num_tokens).reshape(b, f, f, self.num_tokens)
        out = self.decoder.apply({"params": self.params["decoder"]}, onehot)
        return unmap_pixels(jax.nn.sigmoid(out[..., :3]))


# ---------------------------------------------------------------------------
# Taming VQGAN f=16 graph
# ---------------------------------------------------------------------------


class _VQResnetBlock(nn.Module):
    n_out: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.GroupNorm(num_groups=32, name="norm1")(x)
        h = nn.swish(h)
        h = nn.Conv(self.n_out, (3, 3), padding=1, dtype=self.dtype, name="conv1")(h)
        h = nn.GroupNorm(num_groups=32, name="norm2")(h)
        h = nn.swish(h)
        h = nn.Conv(self.n_out, (3, 3), padding=1, dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.n_out:
            x = nn.Conv(self.n_out, (1, 1), dtype=self.dtype, name="nin_shortcut")(x)
        return x + h


class _VQAttnBlock(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        hn = nn.GroupNorm(num_groups=32, name="norm")(x)
        q = nn.Conv(c, (1, 1), dtype=self.dtype, name="q")(hn).reshape(b, h * w, c)
        k = nn.Conv(c, (1, 1), dtype=self.dtype, name="k")(hn).reshape(b, h * w, c)
        v = nn.Conv(c, (1, 1), dtype=self.dtype, name="v")(hn).reshape(b, h * w, c)
        # scores/softmax accumulate in f32 even under a bf16 dtype; the
        # attn @ v contraction keeps cache-dtype multiplicands with f32
        # accumulation (same contract as ops/attention.py)
        attn = jax.nn.softmax(
            jnp.einsum("bic,bjc->bij", q, k,
                       preferred_element_type=jnp.float32) * (c ** -0.5),
            axis=-1)
        o = jnp.einsum("bij,bjc->bic", attn.astype(v.dtype), v,
                       preferred_element_type=jnp.float32
                       ).astype(x.dtype).reshape(b, h, w, c)
        return x + nn.Conv(c, (1, 1), dtype=self.dtype, name="proj_out")(o)


def vqgan_attn_levels(resolution: int, ch_mult: tuple,
                      attn_resolutions: tuple) -> tuple:
    """Encoder level indices that carry per-block AttnBlocks, following
    taming's resolution bookkeeping: level i runs at resolution/2^i, and
    levels whose resolution is in ``attn_resolutions`` interleave attention
    after each res block.  The released f=16/1024 model
    (`vqgan_imagenet_f16_1024`: resolution 256, attn_resolutions [16]) has
    them at encoder level 4 / decoder's lowest level — a converter that
    drops those keys would be silently wrong with the real weights."""
    return tuple(i for i in range(len(ch_mult))
                 if resolution // (2 ** i) in tuple(attn_resolutions))


class VQGanEncoder(nn.Module):
    ch: int = 128
    ch_mult: tuple = (1, 1, 2, 2, 4)
    num_res_blocks: int = 2
    z_channels: int = 256
    resolution: int = 256
    attn_resolutions: tuple = (16,)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        attn_levels = vqgan_attn_levels(self.resolution, self.ch_mult,
                                        self.attn_resolutions)
        h = nn.Conv(self.ch, (3, 3), padding=1, dtype=self.dtype, name="conv_in")(x)
        for i, mult in enumerate(self.ch_mult):
            for b in range(self.num_res_blocks):
                h = _VQResnetBlock(self.ch * mult, dtype=self.dtype,
                                   name=f"down_{i}_block_{b}")(h)
                if i in attn_levels:
                    h = _VQAttnBlock(dtype=self.dtype,
                                     name=f"down_{i}_attn_{b}")(h)
            if i < len(self.ch_mult) - 1:
                h = nn.Conv(self.ch * mult, (3, 3), strides=2, padding=((0, 1), (0, 1)),
                            dtype=self.dtype, name=f"down_{i}_downsample")(h)
        h = _VQResnetBlock(self.ch * self.ch_mult[-1], dtype=self.dtype, name="mid_block_1")(h)
        h = _VQAttnBlock(dtype=self.dtype, name="mid_attn_1")(h)
        h = _VQResnetBlock(self.ch * self.ch_mult[-1], dtype=self.dtype, name="mid_block_2")(h)
        h = nn.GroupNorm(num_groups=32, name="norm_out")(h)
        h = nn.swish(h)
        return nn.Conv(self.z_channels, (3, 3), padding=1, dtype=jnp.float32,
                       name="conv_out")(h)


class VQGanDecoder(nn.Module):
    ch: int = 128
    ch_mult: tuple = (1, 1, 2, 2, 4)
    num_res_blocks: int = 2
    out_ch: int = 3
    resolution: int = 256
    attn_resolutions: tuple = (16,)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z):
        # decoder runs levels highest-mult first; up_{i} here corresponds to
        # taming's up.{n-1-i}, i.e. encoder level n-1-i and its resolution
        attn_levels = vqgan_attn_levels(self.resolution, self.ch_mult,
                                        self.attn_resolutions)
        n = len(self.ch_mult)
        h = nn.Conv(self.ch * self.ch_mult[-1], (3, 3), padding=1,
                    dtype=self.dtype, name="conv_in")(z)
        h = _VQResnetBlock(self.ch * self.ch_mult[-1], dtype=self.dtype, name="mid_block_1")(h)
        h = _VQAttnBlock(dtype=self.dtype, name="mid_attn_1")(h)
        h = _VQResnetBlock(self.ch * self.ch_mult[-1], dtype=self.dtype, name="mid_block_2")(h)
        for i, mult in enumerate(reversed(self.ch_mult)):
            for b in range(self.num_res_blocks + 1):
                h = _VQResnetBlock(self.ch * mult, dtype=self.dtype,
                                   name=f"up_{i}_block_{b}")(h)
                if (n - 1 - i) in attn_levels:
                    h = _VQAttnBlock(dtype=self.dtype,
                                     name=f"up_{i}_attn_{b}")(h)
            if i < len(self.ch_mult) - 1:
                bb, hh, ww, cc = h.shape
                h = jax.image.resize(h, (bb, hh * 2, ww * 2, cc), "nearest")
                h = nn.Conv(cc, (3, 3), padding=1, dtype=self.dtype,
                            name=f"up_{i}_upsample")(h)
        h = nn.GroupNorm(num_groups=32, name="norm_out")(h)
        h = nn.swish(h)
        return nn.Conv(self.out_ch, (3, 3), padding=1, dtype=jnp.float32,
                       name="conv_out")(h)


@dataclasses.dataclass
class VQGanVAE1024:
    """Taming VQGAN wrapper (ref vae.py:132-170)."""

    weights_path: Optional[str] = None
    image_size: int = 256
    num_layers: int = 4       # f = 16 (ref vae.py:156)
    num_tokens: int = 1024
    embed_dim: int = 256
    dtype: Any = jnp.float32

    def __post_init__(self):
        self.encoder = VQGanEncoder(dtype=self.dtype)
        self.decoder = VQGanDecoder(dtype=self.dtype)
        self.params = None
        if self.weights_path is not None:
            from ..utils.checkpoint import load_checkpoint

            self.params = load_checkpoint(self.weights_path)

    def init_random(self, rng):
        f = self.image_size // (2 ** self.num_layers)
        k1, k2, k3 = jax.random.split(rng, 3)
        enc = self.encoder.init(k1, jnp.zeros((1, self.image_size, self.image_size, 3)))
        dec = self.decoder.init(k2, jnp.zeros((1, f, f, self.embed_dim)))
        self.params = {
            "encoder": enc["params"],
            "decoder": dec["params"],
            "codebook": jax.random.normal(k3, (self.num_tokens, self.embed_dim)) * 0.02,
            "quant_proj": {"kernel": jnp.eye(self.embed_dim),
                           "bias": jnp.zeros(self.embed_dim)},
            "post_quant_proj": {"kernel": jnp.eye(self.embed_dim),
                                "bias": jnp.zeros(self.embed_dim)},
        }
        return self.params

    def _require_params(self):
        if self.params is None:
            raise RuntimeError(
                "VQGanVAE1024 needs converted taming-transformers weights "
                "(no network egress here). Run convert_vqgan_weights() on the "
                "released checkpoint and pass weights_path=..., or use "
                "init_random() for graph testing."
            )

    def get_codebook_indices(self, img):
        """Encode + nearest-codebook quantization (ref vae.py:154-159);
        input in [0,1], mapped to [-1,1] as taming expects."""
        self._require_params()
        z = self.encoder.apply({"params": self.params["encoder"]}, 2.0 * img - 1.0)
        z = z @ self.params["quant_proj"]["kernel"] + \
            self.params["quant_proj"]["bias"]
        b, h, w, c = z.shape
        flat = z.reshape(-1, c)
        cb = self.params["codebook"]  # [num_tokens, c]
        d = (
            (flat ** 2).sum(-1, keepdims=True)
            - 2 * flat @ cb.T
            + (cb ** 2).sum(-1)[None, :]
        )
        idx = jnp.argmin(d, axis=-1)
        return idx.reshape(b, h * w).astype(jnp.int32)

    def decode(self, img_seq):
        """Codebook lookup + decoder + [-1,1]->[0,1] clamp (ref vae.py:161-170)."""
        self._require_params()
        b, n = img_seq.shape
        f = int(math.isqrt(n))
        z = jnp.take(self.params["codebook"], img_seq, axis=0).reshape(b, f, f, -1)
        z = z @ self.params["post_quant_proj"]["kernel"] + \
            self.params["post_quant_proj"]["bias"]
        out = self.decoder.apply({"params": self.params["decoder"]}, z)
        return (jnp.clip(out, -1.0, 1.0) + 1.0) * 0.5


# ---------------------------------------------------------------------------
# torch -> JAX weight conversion (runnable wherever the torch ckpts exist)
# ---------------------------------------------------------------------------


def convert_conv_weight(w: np.ndarray) -> np.ndarray:
    """torch conv [out, in, kh, kw] -> flax [kh, kw, in, out]."""
    return np.transpose(w, (2, 3, 1, 0))


def convert_torch_state_dict(state_dict: dict, name_map: dict) -> dict:
    """Generic converter: `name_map` maps flax param paths ('a/b/kernel') to
    torch keys; conv kernels are transposed, linear kernels transposed 2D."""
    out: dict = {}
    for flax_path, torch_key in name_map.items():
        w = np.asarray(state_dict[torch_key])
        if w.ndim == 4:
            w = convert_conv_weight(w)
        elif w.ndim == 2:
            w = w.T
        node = out
        parts = flax_path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = w
    return out
