"""Faithful OpenAI CLIP (ViT-B/32) inference graph in JAX.

The reference's eval harness ranks generated images with the *official*
OpenAI CLIP ViT-B/32 torch package (`/root/reference/genrank.py:20-22,
:68-77`) — a different model from the trainable lucidrains-style `CLIP` in
``models/clip.py``.  This module is a 1:1 JAX graph of the published
architecture so the released weights can be converted
(`tools/convert_weights.py clip`) and used for re-ranking on TPU:

* visual: 32x32 patch conv (no bias) -> class token + positional embedding
  -> ln_pre -> 12x ResidualAttentionBlock (pre-LN, quick-gelu MLP) ->
  ln_post on the class token -> projection;
* text: token + positional embeddings -> 12x causal blocks -> ln_final ->
  features at the EOT (argmax token id) position -> text projection;
* similarity: L2-normalized features, learned exp logit scale.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils.helpers import l2norm


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


@dataclasses.dataclass(frozen=True)
class CLIPViTConfig:
    """ViT-B/32 defaults (the published clip.load('ViT-B/32') geometry)."""

    image_size: int = 224
    patch_size: int = 32
    vision_width: int = 768
    vision_layers: int = 12
    vision_heads: int = 12
    embed_dim: int = 512
    text_width: int = 512
    text_layers: int = 12
    text_heads: int = 8
    context_length: int = 77
    vocab_size: int = 49408
    dtype: Any = jnp.float32

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("dtype")
        return d

    @classmethod
    def from_dict(cls, d: dict, **overrides) -> "CLIPViTConfig":
        d = dict(d)
        d.update(overrides)
        return cls(**d)


class ResidualAttentionBlock(nn.Module):
    """Pre-LN block matching torch CLIP's ResidualAttentionBlock (ln_1 ->
    MultiheadAttention -> ln_2 -> quickgelu MLP)."""

    width: int
    heads: int
    causal: bool = False
    dtype: Any = jnp.float32

    def setup(self):
        w = self.width
        self.ln_1 = nn.LayerNorm(dtype=jnp.float32, name="ln_1")
        self.ln_2 = nn.LayerNorm(dtype=jnp.float32, name="ln_2")
        self.in_proj = nn.Dense(3 * w, dtype=self.dtype, name="in_proj")
        self.out_proj = nn.Dense(w, dtype=self.dtype, name="out_proj")
        self.c_fc = nn.Dense(4 * w, dtype=self.dtype, name="c_fc")
        self.c_proj = nn.Dense(w, dtype=self.dtype, name="c_proj")

    def _attend(self, x):
        b, n, w = x.shape
        dh = w // self.heads
        qkv = self.in_proj(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(b, n, self.heads, dh).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)
        s = jnp.einsum("bhid,bhjd->bhij", q, k,
                       preferred_element_type=jnp.float32) * dh ** -0.5
        if self.causal:
            mask = jnp.tril(jnp.ones((n, n), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        a = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        # bf16 multiplicands, f32 accumulation (the MXU native mode);
        # the result is cast back so out_proj sees the activation dtype
        o = jnp.einsum("bhij,bhjd->bhid", a, v,
                       preferred_element_type=jnp.float32).astype(x.dtype)
        o = o.transpose(0, 2, 1, 3).reshape(b, n, w)
        return self.out_proj(o)

    def __call__(self, x):
        x = x + self._attend(self.ln_1(x).astype(x.dtype))
        h = self.c_fc(self.ln_2(x).astype(x.dtype))
        x = x + self.c_proj(quick_gelu(h))
        return x


class CLIPViT(nn.Module):
    """Inference-only OpenAI CLIP graph (weights converted from torch)."""

    cfg: CLIPViTConfig

    def setup(self):
        cfg = self.cfg
        grid = cfg.image_size // cfg.patch_size
        init = nn.initializers.normal(0.02)
        self.conv1 = nn.Conv(cfg.vision_width,
                             (cfg.patch_size, cfg.patch_size),
                             strides=cfg.patch_size, use_bias=False,
                             padding="VALID", dtype=cfg.dtype, name="conv1")
        self.class_embedding = self.param("class_embedding", init,
                                          (cfg.vision_width,))
        self.vision_pos = self.param("vision_pos", init,
                                     (grid * grid + 1, cfg.vision_width))
        self.ln_pre = nn.LayerNorm(dtype=jnp.float32, name="ln_pre")
        self.vision_blocks = [
            ResidualAttentionBlock(cfg.vision_width, cfg.vision_heads,
                                   dtype=cfg.dtype, name=f"vision_block_{i}")
            for i in range(cfg.vision_layers)]
        self.ln_post = nn.LayerNorm(dtype=jnp.float32, name="ln_post")
        self.vision_proj = self.param("vision_proj", init,
                                      (cfg.vision_width, cfg.embed_dim))

        self.token_embedding = nn.Embed(cfg.vocab_size, cfg.text_width,
                                        embedding_init=init,
                                        name="token_embedding")
        self.text_pos = self.param("text_pos", init,
                                   (cfg.context_length, cfg.text_width))
        self.text_blocks = [
            ResidualAttentionBlock(cfg.text_width, cfg.text_heads,
                                   causal=True, dtype=cfg.dtype,
                                   name=f"text_block_{i}")
            for i in range(cfg.text_layers)]
        self.ln_final = nn.LayerNorm(dtype=jnp.float32, name="ln_final")
        self.text_projection = self.param("text_projection", init,
                                          (cfg.text_width, cfg.embed_dim))
        self.logit_scale = self.param("logit_scale",
                                      nn.initializers.constant(4.6052), ())

    def encode_image(self, image):
        """image: [b, H, W, 3], CLIP-normalized. -> [b, embed_dim]."""
        x = self.conv1(image)                    # [b, g, g, w]
        b, g1, g2, w = x.shape
        x = x.reshape(b, g1 * g2, w)
        cls = jnp.broadcast_to(self.class_embedding, (b, 1, w)).astype(x.dtype)
        x = jnp.concatenate([cls, x], axis=1) + self.vision_pos
        x = self.ln_pre(x).astype(x.dtype)
        for blk in self.vision_blocks:
            x = blk(x)
        pooled = self.ln_post(x[:, 0]).astype(jnp.float32)
        return pooled @ self.vision_proj

    def encode_text(self, text):
        """text: [b, context_length] int tokens. -> [b, embed_dim]."""
        x = self.token_embedding(text) + self.text_pos[: text.shape[1]]
        x = x.astype(self.cfg.dtype)
        for blk in self.text_blocks:
            x = blk(x)
        x = self.ln_final(x).astype(jnp.float32)
        eot = jnp.argmax(text, axis=-1)          # EOT has the largest id
        pooled = jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]
        return pooled @ self.text_projection

    def __call__(self, text, image):
        """-> (logits_per_text [bt, bi], logits_per_image [bi, bt])."""
        t = l2norm(self.encode_text(text))
        i = l2norm(self.encode_image(image))
        scale = jnp.exp(self.logit_scale)
        logits_per_text = scale * t @ i.T
        return logits_per_text, logits_per_text.T
