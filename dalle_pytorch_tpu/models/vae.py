"""DiscreteVAE — gumbel-softmax discrete image tokenizer, TPU-native.

Capability parity with the reference `DiscreteVAE`
(`/root/reference/dalle_pytorch/dalle_pytorch.py:54-205`), redesigned for
XLA:TPU:

* NHWC layout (XLA:TPU's preferred conv layout) instead of torch NCHW.
* Functional flax module: explicit params, explicit RNG for the gumbel
  sampling, no `.training` flags or in-place tensor ops.
* Mixed precision: bf16 activations (MXU) with f32 params by default.

Behavioral invariants preserved (see SURVEY.md §7):
* encoder = num_layers x (conv k4 s2 'same-1' + relu) [+ resblocks] + 1x1 conv
  -> num_tokens logits (ref :98-126).
* loss = recon (MSE or huber) + kl_div_loss_weight * KL(q || uniform) where
  the KL reduction is torch 'batchmean': summed over positions and vocab,
  divided by batch (ref :189-200).
* gumbel-softmax with temperature + optional hard straight-through
  (ref :182-184).
* `get_codebook_indices` = argmax of encoder logits, flattened row-major
  (ref :144-149); `decode` embeds codes and runs the decoder (ref :151-161).
* per-channel input normalization inside the model (ref :134-142).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..obs import prof
from ..utils.helpers import default


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    """Hyperparameters; field names mirror the reference's ctor kwargs
    (ref dalle_pytorch.py:69-83) so checkpoints carry identical `hparams`."""

    image_size: int = 256
    num_tokens: int = 512
    codebook_dim: int = 512
    num_layers: int = 3
    num_resnet_blocks: int = 0
    hidden_dim: int = 64
    channels: int = 3
    smooth_l1_loss: bool = False
    temperature: float = 0.9
    straight_through: bool = False
    kl_div_loss_weight: float = 0.0
    normalization: Optional[Tuple[Sequence[float], Sequence[float]]] = (
        (0.5, 0.5, 0.5),
        (0.5, 0.5, 0.5),
    )
    dtype: Any = jnp.float32

    def __post_init__(self):
        assert math.log2(self.image_size).is_integer(), "image size must be a power of 2"
        assert self.num_layers >= 1, "number of layers must be >= 1"

    @property
    def fmap_size(self) -> int:
        return self.image_size // (2 ** self.num_layers)

    @property
    def image_seq_len(self) -> int:
        return self.fmap_size ** 2

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("dtype")
        return d

    @classmethod
    def from_dict(cls, d: dict, **overrides) -> "VAEConfig":
        d = dict(d)
        if d.get("normalization") is not None:
            means, stds = d["normalization"]
            d["normalization"] = (tuple(means), tuple(stds))
        d.update(overrides)
        return cls(**d)


class ResBlock(nn.Module):
    """conv3-relu-conv3-relu-conv1 residual block (ref dalle_pytorch.py:54-66)."""

    chan: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.chan, (3, 3), padding=1, dtype=self.dtype)(x)
        h = nn.relu(h)
        h = nn.Conv(self.chan, (3, 3), padding=1, dtype=self.dtype)(h)
        h = nn.relu(h)
        h = nn.Conv(self.chan, (1, 1), dtype=self.dtype)(h)
        return h + x


class Encoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        with prof.scope("vae-conv"):
            for _ in range(cfg.num_layers):
                x = nn.Conv(cfg.hidden_dim, (4, 4), strides=2, padding=1,
                            dtype=cfg.dtype)(x)
                x = nn.relu(x)
            for _ in range(cfg.num_resnet_blocks):
                x = ResBlock(cfg.hidden_dim, dtype=cfg.dtype)(x)
            # 1x1 conv head to codebook logits; keep the head in f32 for a
            # stable gumbel-softmax even when the trunk runs in bf16.
            x = nn.Conv(cfg.num_tokens, (1, 1), dtype=jnp.float32)(x)
            return x  # [b, h, w, num_tokens]


class Decoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        has_resblocks = cfg.num_resnet_blocks > 0
        with prof.scope("vae-conv"):
            if has_resblocks:
                x = nn.Conv(cfg.hidden_dim, (1, 1), dtype=cfg.dtype)(x)
                for _ in range(cfg.num_resnet_blocks):
                    x = ResBlock(cfg.hidden_dim, dtype=cfg.dtype)(x)
            for _ in range(cfg.num_layers):
                x = nn.ConvTranspose(cfg.hidden_dim, (4, 4), strides=(2, 2),
                                     padding="SAME", dtype=cfg.dtype)(x)
                x = nn.relu(x)
            x = nn.Conv(cfg.channels, (1, 1), dtype=jnp.float32)(x)
            return x  # [b, H, W, channels]


def gumbel_softmax(logits, key, tau, hard, axis=-1):
    """Gumbel-softmax sample; `hard` adds the straight-through estimator
    (equivalent of torch F.gumbel_softmax, ref dalle_pytorch.py:182)."""
    gumbels = jax.random.gumbel(key, logits.shape, dtype=logits.dtype)
    y_soft = jax.nn.softmax((logits + gumbels) / tau, axis=axis)
    if not hard:
        return y_soft
    idx = jnp.argmax(y_soft, axis=axis)
    y_hard = jax.nn.one_hot(idx, logits.shape[axis], axis=axis, dtype=logits.dtype)
    return y_hard + y_soft - jax.lax.stop_gradient(y_soft)


class DiscreteVAE(nn.Module):
    """See module docstring. Images are NHWC float in [0, 1]."""

    cfg: VAEConfig

    def setup(self):
        cfg = self.cfg
        # N(0,1) init for parity with torch nn.Embedding (ref :94) — the
        # codebook magnitude drives the gradient signal into the encoder.
        self.codebook = nn.Embed(cfg.num_tokens, cfg.codebook_dim,
                                 embedding_init=nn.initializers.normal(1.0),
                                 name="codebook")
        self.encoder = Encoder(cfg, name="encoder")
        self.decoder = Decoder(cfg, name="decoder")

    # ref dalle_pytorch.py:134-142
    def norm(self, images):
        if self.cfg.normalization is None:
            return images
        means, stds = self.cfg.normalization
        means = jnp.asarray(means, images.dtype)
        stds = jnp.asarray(stds, images.dtype)
        return (images - means) / stds

    def encode_logits(self, img):
        """Encoder logits [b, h, w, num_tokens] (ref forward(return_logits=True))."""
        return self.encoder(self.norm(img).astype(self.cfg.dtype))

    def get_codebook_indices(self, img):
        """Hard token ids [b, image_seq_len] (ref :144-149)."""
        logits = self.encode_logits(img)
        b, h, w, _ = logits.shape
        return jnp.argmax(logits, axis=-1).reshape(b, h * w).astype(jnp.int32)

    def decode(self, img_seq):
        """Token ids [b, n] -> images [b, H, W, c] (ref :151-161)."""
        b, n = img_seq.shape
        h = w = int(math.isqrt(n))
        with prof.scope("vae-codebook"):
            embeds = self.codebook(img_seq).reshape(b, h, w,
                                                    self.cfg.codebook_dim)
        return self.decoder(embeds.astype(self.cfg.dtype))

    def __call__(self, img, *, rng=None, return_loss=False, return_recons=False,
                 return_logits=False, temp=None):
        cfg = self.cfg
        assert img.shape[1] == cfg.image_size and img.shape[2] == cfg.image_size, (
            f"input must have the correct image size {cfg.image_size}"
        )

        logits = self.encode_logits(img)
        if return_logits:
            return logits

        temp = default(temp, cfg.temperature)
        if rng is None:
            rng = self.make_rng("gumbel")
        with prof.scope("vae-codebook"):
            soft_one_hot = gumbel_softmax(logits, rng, tau=temp,
                                          hard=cfg.straight_through)
            # [b,h,w,n] @ [n,d] -> [b,h,w,d]; large matmul, lands on the MXU.
            sampled = jnp.einsum(
                "bhwn,nd->bhwd", soft_one_hot,
                self.codebook.embedding.astype(soft_one_hot.dtype),
                preferred_element_type=jnp.float32,
            )
        out = self.decoder(sampled.astype(cfg.dtype))

        if not return_loss:
            return out

        with prof.scope("vae-loss"):
            target = self.norm(img).astype(jnp.float32)
            out_f32 = out.astype(jnp.float32)
            if cfg.smooth_l1_loss:
                diff = jnp.abs(out_f32 - target)
                recon_loss = jnp.where(diff < 1.0, 0.5 * diff ** 2,
                                       diff - 0.5).mean()
            else:
                recon_loss = ((out_f32 - target) ** 2).mean()

            # KL(q || uniform), torch-'batchmean' reduction (ref :193-198).
            b = logits.shape[0]
            logits_flat = logits.reshape(b, -1,
                                         cfg.num_tokens).astype(jnp.float32)
            log_qy = jax.nn.log_softmax(logits_flat, axis=-1)
            log_uniform = -jnp.log(float(cfg.num_tokens))
            kl_div = (jnp.exp(log_qy) * (log_qy - log_uniform)).sum() / b

            loss = recon_loss + kl_div * cfg.kl_div_loss_weight
        if not return_recons:
            return loss
        return loss, out
